"""The analyzer proves itself against the bugs it encodes: every
checker fires on its bad fixture (including the PRE-FIX forms of the
two real round-5 bugs, reconstructed from the live files) and stays
silent on the good one."""

import os
import textwrap

import pytest

from rafiki_tpu.analysis import analyze_paths, load_builtin_checkers
from rafiki_tpu.analysis.core import REGISTRY, module_name_for

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

load_builtin_checkers()


def _ids(result, path=None):
    return sorted({f.checker_id for f in result.unsuppressed
                   if path is None or f.path == str(path)})


def _analyze_snippet(tmp_path, source, name="snippet.py", select=None):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    return analyze_paths([str(f)], select=select)


def test_all_builtin_checkers_registered():
    assert {"RF001", "RF002", "RF003", "RF004", "RF005", "RF006",
            "RF007", "RF008", "RF009", "RF010", "RF011",
            "RF012", "RF013", "RF014", "RF015", "RF016",
            "RF017", "RF018", "RF019"} <= set(REGISTRY)


# ---------------------------------------------------------------------------
# RF001 entrypoint-platform-pin
# ---------------------------------------------------------------------------


def test_rf001_fires_on_unpinned_jax_entrypoint(tmp_path):
    r = _analyze_snippet(tmp_path, """
        import jax

        def run_worker_process(meta_path):
            return jax.devices()

        def main():
            run_worker_process("x")

        if __name__ == "__main__":
            main()
        """)
    # run_*_process AND main AND the __main__ block (whose only call,
    # main(), does not pin) are all unpinned
    assert [f.checker_id for f in r.unsuppressed].count("RF001") == 3


def test_rf001_quiet_when_pinned_before_touch(tmp_path):
    r = _analyze_snippet(tmp_path, """
        import jax
        from rafiki_tpu.utils.backend import honor_env_platform

        def main():
            honor_env_platform()
            return jax.devices()

        if __name__ == "__main__":
            main()
        """)
    assert "RF001" not in _ids(r)


def test_rf001_fires_when_jax_touched_before_pin(tmp_path):
    r = _analyze_snippet(tmp_path, """
        import jax
        from rafiki_tpu.utils.backend import honor_env_platform

        def main():
            devices = jax.devices()
            honor_env_platform()
            return devices
        """)
    found = [f for f in r.unsuppressed if f.checker_id == "RF001"]
    assert len(found) == 1 and "before the platform pin" in found[0].message


def test_rf001_pin_through_local_helper_chain(tmp_path):
    # bench.py's shape: main -> _init_backend -> honor_env_platform
    r = _analyze_snippet(tmp_path, """
        import jax

        def _init_backend():
            from rafiki_tpu.utils.backend import honor_env_platform
            honor_env_platform()

        def main():
            _init_backend()
            return jax.devices()

        if __name__ == "__main__":
            main()
        """)
    assert "RF001" not in _ids(r)


def test_rf001_ignores_jaxfree_entrypoints(tmp_path):
    r = _analyze_snippet(tmp_path, """
        import json

        def main():
            print(json.dumps({}))

        if __name__ == "__main__":
            main()
        """)
    assert "RF001" not in _ids(r)


def test_rf001_real_prefix_inference_worker(tmp_path):
    """The round-5 bug verbatim: worker/inference.py WITHOUT the
    honor_env_platform() call, analyzed against the real tree (the jax
    taint arrives transitively through rafiki_tpu.model.base)."""
    live = open(os.path.join(REPO, "rafiki_tpu/worker/inference.py")).read()
    assert "honor_env_platform" in live  # the fix is present today
    prefix = "\n".join(l for l in live.splitlines()
                       if "honor_env_platform" not in l)
    bad = tmp_path / "inference_prefix.py"
    bad.write_text(prefix)
    r = analyze_paths([str(bad), os.path.join(REPO, "rafiki_tpu")],
                      select=["RF001"])
    mine = [f for f in r.unsuppressed if f.path == str(bad)]
    assert [f.checker_id for f in mine] == ["RF001"]
    assert "run_inference_worker_process" in mine[0].message


def test_rf001_current_inference_worker_is_clean():
    r = analyze_paths([os.path.join(REPO, "rafiki_tpu")], select=["RF001"])
    assert [f for f in r.unsuppressed
            if f.path.endswith("worker/inference.py")] == []


# ---------------------------------------------------------------------------
# RF002 platform-literal-gate
# ---------------------------------------------------------------------------


def test_rf002_fires_on_tpu_literal_compare(tmp_path):
    r = _analyze_snippet(tmp_path, """
        def gate(platform):
            if platform == "tpu":
                return 1
            if "tpu" != platform:
                return 2
        """)
    assert [f.checker_id for f in r.unsuppressed] == ["RF002", "RF002"]


def test_rf002_quiet_on_cpu_gate_and_membership(tmp_path):
    r = _analyze_snippet(tmp_path, """
        def gate(platform, device_kind):
            on_accel = platform != "cpu"
            return on_accel or "TPU" in device_kind or platform in ("tpu",)
        """)
    assert "RF002" not in _ids(r)


def test_rf002_real_prefix_bench_mfu_gate(tmp_path):
    """The round-5 bug verbatim: bench.py's MFU gate reverted to the
    == "tpu" form that nulled MFU under this image's "axon" platform."""
    live = open(os.path.join(REPO, "bench.py")).read()
    assert 'sc["platform"] != "cpu"' in live  # the fix is present today
    prefix = live.replace('sc["platform"] != "cpu"', 'sc["platform"] == "tpu"')
    bad = tmp_path / "bench_prefix.py"
    bad.write_text(prefix)
    r = analyze_paths([str(bad)], select=["RF002"])
    assert [f.checker_id for f in r.unsuppressed] == ["RF002"]


def test_rf002_current_bench_is_clean():
    r = analyze_paths([os.path.join(REPO, "bench.py")], select=["RF002"])
    assert r.unsuppressed == []


# ---------------------------------------------------------------------------
# RF003 defaultdict-read-leak
# ---------------------------------------------------------------------------

RF003_BAD = """
    from collections import defaultdict

    class Bus:
        def __init__(self):
            self._workers = defaultdict(set)

        def get_workers(self, job_id):
            return sorted(self._workers[job_id])

        def heartbeat(self, job_id, worker_id):
            if worker_id in self._workers[job_id]:
                pass
    """

RF003_GOOD = """
    from collections import defaultdict

    class Bus:
        def __init__(self):
            self._workers = defaultdict(set)
            self._plain = {}

        def add_worker(self, job_id, worker_id):
            self._workers[job_id].add(worker_id)

        def get_workers(self, job_id):
            return sorted(self._workers.get(job_id, ()))

        def read_plain(self, job_id):
            return self._plain[job_id]
    """


def test_rf003_fires_on_read_side_subscript(tmp_path):
    r = _analyze_snippet(tmp_path, RF003_BAD)
    assert [f.checker_id for f in r.unsuppressed] == ["RF003", "RF003"]


def test_rf003_quiet_on_insert_idiom_and_get(tmp_path):
    r = _analyze_snippet(tmp_path, RF003_GOOD)
    assert "RF003" not in _ids(r)


def test_rf003_current_bus_queues_is_clean():
    """The live bus keeps the read-side fix: heartbeat/get_workers use
    ``.get(job_id, ...)`` instead of defaultdict subscripts, so probing
    rotating job ids cannot leak empty registry entries."""
    live = os.path.join(REPO, "rafiki_tpu", "bus", "queues.py")
    r = analyze_paths([live], select=["RF003"])
    assert r.unsuppressed == []


# ---------------------------------------------------------------------------
# RF004 unguarded-shared-mutation
# ---------------------------------------------------------------------------

RF004_BAD = """
    import threading

    class Registry:
        def __init__(self):
            self._lock = threading.Lock()
            self._counters = {}
            self._events = []

        def inc(self, name):
            self._counters[name] = self._counters.get(name, 0) + 1

        def log(self, ev):
            self._events.append(ev)
    """

RF004_GOOD = """
    import threading

    class Registry:
        def __init__(self):
            self._lock = threading.Lock()
            self._counters = {}
            self._events = []

        def inc(self, name):
            with self._lock:
                self._counters[name] = self._counters.get(name, 0) + 1

        def log(self, ev):
            with self._lock:
                self._events.append(ev)

    class NoLockNoRules:
        def __init__(self):
            self._events = []

        def log(self, ev):
            self._events.append(ev)
    """


def test_rf004_fires_on_unlocked_mutation(tmp_path):
    r = _analyze_snippet(tmp_path, RF004_BAD)
    assert [f.checker_id for f in r.unsuppressed] == ["RF004", "RF004"]


def test_rf004_quiet_under_lock_and_in_lockless_classes(tmp_path):
    r = _analyze_snippet(tmp_path, RF004_GOOD)
    assert "RF004" not in _ids(r)


def test_rf004_condition_counts_as_lock(tmp_path):
    r = _analyze_snippet(tmp_path, """
        import threading

        class Slots:
            def __init__(self):
                self._cv = threading.Condition()
                self._preds = {}

            def put(self, k, v):
                with self._cv:
                    self._preds.setdefault(k, []).append(v)
                    self._cv.notify_all()
        """)
    assert "RF004" not in _ids(r)


# ---------------------------------------------------------------------------
# RF005 jit-hazard
# ---------------------------------------------------------------------------

RF005_BAD = """
    import jax
    import numpy as np

    def train_step(state, batch):
        if state > 0:
            state = state - 1
        loss = float(batch.mean())
        host = np.asarray(batch)
        return state, loss, host

    train_step = jax.jit(train_step)

    def rebuild_per_iteration(xs):
        outs = []
        for x in xs:
            outs.append(jax.jit(lambda v: v + 1)(x))
        return outs
    """

RF005_GOOD = """
    import jax
    import jax.numpy as jnp

    def train_step(state, batch):
        state = jnp.where(state > 0, state - 1, state)
        if "valid" in batch:
            pass
        return state

    train_step = jax.jit(train_step)

    _step = jax.jit(lambda v: v + 1)

    def apply_all(xs):
        return [float(_step(x)) for x in xs]
    """


def test_rf005_fires_on_branch_sync_and_jit_in_loop(tmp_path):
    r = _analyze_snippet(tmp_path, RF005_BAD)
    msgs = [f.message for f in r.unsuppressed if f.checker_id == "RF005"]
    assert any("python `if`" in m for m in msgs)
    assert any("host sync `float" in m for m in msgs)
    assert any("host sync `np.asarray" in m for m in msgs)
    assert any("inside a loop" in m for m in msgs)


def test_rf005_quiet_on_device_side_idioms(tmp_path):
    r = _analyze_snippet(tmp_path, RF005_GOOD)
    assert "RF005" not in _ids(r)


def test_rf005_ops_train_is_clean():
    r = analyze_paths([os.path.join(REPO, "rafiki_tpu/ops"),
                       os.path.join(REPO, "rafiki_tpu/parallel")],
                      select=["RF005"])
    assert r.unsuppressed == []


# ---------------------------------------------------------------------------
# RF006 swallowed-interrupt
# ---------------------------------------------------------------------------


def test_rf006_fires_on_swallowed_base_exception(tmp_path):
    r = _analyze_snippet(tmp_path, """
        def supervise():
            try:
                work()
            except BaseException:
                log("oops")
        """, select=["RF006"])
    assert len(r.unsuppressed) == 1
    assert r.unsuppressed[0].severity == "error"


def test_rf006_fires_on_bare_except_and_interrupt_tuple(tmp_path):
    r = _analyze_snippet(tmp_path, """
        def a():
            try:
                work()
            except:
                pass

        def b():
            try:
                work()
            except (ValueError, KeyboardInterrupt):
                pass
        """, select=["RF006"])
    assert len(r.unsuppressed) == 2


def test_rf006_quiet_on_catch_log_reraise_and_exits(tmp_path):
    r = _analyze_snippet(tmp_path, """
        import os
        import sys

        def supervise():
            try:
                work()
            except BaseException:
                mark_errored()
                raise

        def run():
            while True:
                try:
                    step()
                except BaseException:
                    return

        def watchdog():
            try:
                work()
            except BaseException:
                os._exit(17)
        """, select=["RF006"])
    assert r.unsuppressed == []


def test_rf006_conditional_reraise_is_clean(tmp_path):
    # The services-manager fix shape: record, then re-raise interrupts.
    r = _analyze_snippet(tmp_path, """
        def run():
            try:
                work()
            except BaseException as e:
                record(e)
                if not isinstance(e, Exception):
                    raise
        """, select=["RF006"])
    assert r.unsuppressed == []


def test_rf006_warns_on_silent_swallow_in_loop_function(tmp_path):
    r = _analyze_snippet(tmp_path, """
        def run():
            while True:
                try:
                    step()
                except Exception:
                    continue

        def saver_loop():
            while alive():
                try:
                    persist()
                except Exception:
                    pass
        """, select=["RF006"])
    assert len(r.unsuppressed) == 2
    assert all(f.severity == "warning" for f in r.unsuppressed)


def test_rf006_quiet_on_handled_swallow_and_non_loop_functions(tmp_path):
    r = _analyze_snippet(tmp_path, """
        def run():
            while True:
                try:
                    step()
                except Exception as e:
                    count(e)  # absorbed but accounted for

        def helper():  # not a long-running-loop name
            while True:
                try:
                    step()
                except Exception:
                    pass

        def run_once():
            try:  # not inside a while loop
                step()
            except Exception:
                pass
        """, select=["RF006"])
    assert r.unsuppressed == []


def test_rf006_live_tree_is_clean():
    """The violations RF006 found in this repo are fixed or carry a
    justified suppression — and stay that way."""
    r = analyze_paths([os.path.join(REPO, "rafiki_tpu"),
                       os.path.join(REPO, "scripts"),
                       os.path.join(REPO, "bench.py")],
                      select=["RF006"])
    assert r.unsuppressed == []


# ---------------------------------------------------------------------------
# suppressions / cli / misc
# ---------------------------------------------------------------------------


def test_suppression_with_justification_suppresses(tmp_path):
    r = _analyze_snippet(tmp_path, """
        def gate(platform):
            # lint: disable=RF002 — exercised by the suppression test
            return platform == "tpu"
        """)
    assert r.unsuppressed == []
    assert len(r.findings) == 1 and r.findings[0].suppressed
    assert "suppression test" in r.findings[0].justification


def test_suppression_without_justification_does_not_suppress(tmp_path):
    r = _analyze_snippet(tmp_path, """
        def gate(platform):
            return platform == "tpu"  # lint: disable=RF002
        """)
    assert len(r.unsuppressed) == 1
    assert "no justification" in r.unsuppressed[0].message


# ---------------------------------------------------------------------------
# RF008 metric-name-drift
# ---------------------------------------------------------------------------


def test_rf008_fires_on_dynamic_metric_names(tmp_path):
    r = _analyze_snippet(tmp_path, """
        from rafiki_tpu import telemetry

        def f(site, mode, n):
            telemetry.inc(f"chaos.injected.{site}.{mode}")
            name = "worker." + str(n)
            telemetry.observe(name, 1.0)
            telemetry.set_gauge("bus." + "depth", 2)
        """)
    assert [f.checker_id for f in r.unsuppressed] == ["RF008"] * 3


def test_rf008_quiet_on_static_names(tmp_path):
    r = _analyze_snippet(tmp_path, """
        from rafiki_tpu import telemetry

        COLD_METRIC = "train.cold_epoch_s"

        class Names:
            EPOCH = "train.epoch_s"

        def f(cold):
            telemetry.inc("train.epochs")
            telemetry.observe(COLD_METRIC if cold else Names.EPOCH, 1.0)
            with telemetry.span("worker.epoch"):
                pass
        """)
    assert "RF008" not in _ids(r)


def test_rf008_tracks_from_import_aliases(tmp_path):
    r = _analyze_snippet(tmp_path, """
        from rafiki_tpu.telemetry import inc as bump

        def f(reason):
            bump(f"gateway.shed.{reason}")
        """)
    assert [f.checker_id for f in r.unsuppressed] == ["RF008"]


def test_rf008_justified_suppression_honored(tmp_path):
    r = _analyze_snippet(tmp_path, """
        from rafiki_tpu import telemetry

        def f(reason):
            # lint: disable=RF008 — bounded shed-reason enum
            telemetry.inc(f"gateway.shed.{reason}")
        """)
    assert "RF008" not in _ids(r)


def test_rf008_exempts_the_registry_itself(tmp_path):
    obs = tmp_path / "rafiki_tpu" / "obs"
    obs.mkdir(parents=True)
    (tmp_path / "rafiki_tpu" / "__init__.py").write_text("")
    (obs / "__init__.py").write_text("")  # module_name_for walks these
    f = obs / "inner.py"
    f.write_text("from rafiki_tpu import telemetry\n\n"
                 "def flush(name):\n"
                 "    telemetry.inc(f\"obs.flush.{name}\")\n")
    r = analyze_paths([str(f)], select=["RF008"])
    assert "RF008" not in _ids(r)


def test_rf008_current_tree_is_clean():
    r = analyze_paths([os.path.join(REPO, "rafiki_tpu"),
                       os.path.join(REPO, "bench.py"),
                       os.path.join(REPO, "scripts")], select=["RF008"])
    mine = [f for f in r.unsuppressed if f.checker_id == "RF008"]
    assert mine == [], [f"{f.path}:{f.line}" for f in mine]


# ---------------------------------------------------------------------------
# RF009 wall-clock-duration
# ---------------------------------------------------------------------------


def test_rf009_fires_on_wall_clock_delta(tmp_path):
    r = _analyze_snippet(tmp_path, """
        import time

        def measure(work):
            t0 = time.time()
            work()
            return time.time() - t0
        """)
    found = [f for f in r.unsuppressed if f.checker_id == "RF009"]
    assert len(found) == 1 and "monotonic" in found[0].message


def test_rf009_quiet_on_legal_wall_clock_shapes(tmp_path):
    # deadline - time.time() (remaining budget against an absolute
    # cutoff), bare timestamps, and monotonic deltas are all fine.
    r = _analyze_snippet(tmp_path, """
        import time

        def remaining(deadline):
            return deadline - time.time()

        def stamp(rec):
            rec["ts"] = time.time()
            return rec

        def measure(work):
            t0 = time.monotonic()
            work()
            return time.monotonic() - t0
        """)
    assert "RF009" not in _ids(r)


def test_rf009_justified_suppression_honored(tmp_path):
    r = _analyze_snippet(tmp_path, """
        import time

        def lease_cutoff(max_age_s):
            # lint: disable=RF009 — cutoff vs cross-process wall-clock beats
            return time.time() - max_age_s
        """)
    assert "RF009" not in _ids(r)


def test_rf009_current_tree_is_clean():
    r = analyze_paths([os.path.join(REPO, "rafiki_tpu"),
                       os.path.join(REPO, "bench.py"),
                       os.path.join(REPO, "scripts")], select=["RF009"])
    mine = [f for f in r.unsuppressed if f.checker_id == "RF009"]
    assert mine == [], [f"{f.path}:{f.line}" for f in mine]


# ---------------------------------------------------------------------------
# RF010 nondeterministic-sim
# ---------------------------------------------------------------------------


def _twin_snippet(tmp_path, source, select=None):
    """Write the snippet INSIDE a rafiki_tpu/obs/twin/ package tree so
    module_name_for resolves it into RF010's scope."""
    twin = tmp_path / "rafiki_tpu" / "obs" / "twin"
    twin.mkdir(parents=True)
    for d in (tmp_path / "rafiki_tpu", tmp_path / "rafiki_tpu" / "obs",
              twin):
        (d / "__init__.py").write_text("")
    f = twin / "snippet.py"
    f.write_text(textwrap.dedent(source))
    return analyze_paths([str(f)], select=select)


RF010_BAD = """
    import random
    import time

    def simulate_badly(n):
        rng = random.Random()            # OS entropy
        jitter = random.random()         # global stream
        t0 = time.monotonic()            # ambient clock
        return rng, jitter, t0
    """


def test_rf010_fires_on_each_entropy_source(tmp_path):
    r = _twin_snippet(tmp_path, RF010_BAD)
    found = [f for f in r.unsuppressed if f.checker_id == "RF010"]
    assert len(found) == 3
    messages = " ".join(f.message for f in found)
    assert "OS entropy" in messages
    assert "GLOBAL random stream" in messages
    assert "ambient clock" in messages


def test_rf010_scoped_to_twin_package_only(tmp_path):
    # The identical source OUTSIDE rafiki_tpu/obs/twin/ is legal:
    # entropy is only a defect where determinism is the contract.
    r = _analyze_snippet(tmp_path, RF010_BAD)
    assert "RF010" not in _ids(r)


def test_rf010_quiet_on_seeded_streams(tmp_path):
    r = _twin_snippet(tmp_path, """
        import random

        def simulate(seed, samples):
            rng = random.Random(f"{seed}:service")
            return samples[rng.randrange(len(samples))] + rng.random()
        """)
    assert "RF010" not in _ids(r)


def _train_twin_snippet(tmp_path, source, select=None):
    """Same as _twin_snippet but one level deeper — the train twin
    subpackage inherits the determinism contract verbatim."""
    train = tmp_path / "rafiki_tpu" / "obs" / "twin" / "train"
    train.mkdir(parents=True)
    for d in (tmp_path / "rafiki_tpu", tmp_path / "rafiki_tpu" / "obs",
              train.parent, train):
        (d / "__init__.py").write_text("")
    f = train / "snippet.py"
    f.write_text(textwrap.dedent(source))
    return analyze_paths([str(f)], select=select)


def test_rf010_covers_train_subpackage(tmp_path):
    r = _train_twin_snippet(tmp_path, RF010_BAD)
    found = [f for f in r.unsuppressed if f.checker_id == "RF010"]
    assert len(found) == 3
    messages = " ".join(f.message for f in found)
    assert "OS entropy" in messages
    assert "GLOBAL random stream" in messages
    assert "ambient clock" in messages


def test_rf010_justified_suppression_honored(tmp_path):
    r = _twin_snippet(tmp_path, """
        import time

        def artifact(doc):
            # lint: disable=RF010 — metadata stamp, not simulation state
            doc["created_ts"] = time.time()
            return doc
        """)
    assert "RF010" not in _ids(r)


def test_rf010_current_tree_is_clean():
    r = analyze_paths([os.path.join(REPO, "rafiki_tpu"),
                       os.path.join(REPO, "bench.py"),
                       os.path.join(REPO, "scripts")], select=["RF010"])
    mine = [f for f in r.unsuppressed if f.checker_id == "RF010"]
    assert mine == [], [f"{f.path}:{f.line}" for f in mine]


def test_suppression_only_covers_named_ids(tmp_path):
    r = _analyze_snippet(tmp_path, """
        def gate(platform):
            # lint: disable=RF005 — wrong id on purpose
            return platform == "tpu"
        """)
    assert [f.checker_id for f in r.unsuppressed] == ["RF002"]


def test_select_runs_only_requested_checkers(tmp_path):
    f = tmp_path / "both.py"
    f.write_text('import jax\n\ndef main():\n    return jax.devices()\n'
                 '\nx = "x" == "tpu"\n')
    r = analyze_paths([str(f)], select=["RF002"])
    assert _ids(r) == ["RF002"]


def test_module_name_for_package_files():
    assert module_name_for(
        os.path.join(REPO, "rafiki_tpu/bus/queues.py")) == "rafiki_tpu.bus.queues"
    assert module_name_for(os.path.join(REPO, "bench.py")) == "bench"


def test_cli_json_and_exit_codes(tmp_path, capsys):
    import json as _json

    from rafiki_tpu.analysis.cli import main

    bad = tmp_path / "bad.py"
    bad.write_text('def gate(p):\n    return p == "tpu"\n')
    assert main([str(bad), "--format", "json"]) == 1
    payload = _json.loads(capsys.readouterr().out)
    assert payload["unsuppressed"] == 1
    assert payload["findings"][0]["checker"] == "RF002"

    good = tmp_path / "good.py"
    good.write_text('def gate(p):\n    return p != "cpu"\n')
    assert main([str(good), "--format", "json"]) == 0

    assert main([str(good), "--select", "NOPE01"]) == 2


# ---------------------------------------------------------------------------
# RF011 unjournaled-decision
# ---------------------------------------------------------------------------


def _advisor_snippet(tmp_path, source, select=None):
    """Write the snippet INSIDE a rafiki_tpu/advisor/ package tree so
    module_name_for resolves it into RF011's scope."""
    adv = tmp_path / "rafiki_tpu" / "advisor"
    adv.mkdir(parents=True)
    for d in (tmp_path / "rafiki_tpu", adv):
        (d / "__init__.py").write_text("")
    f = adv / "snippet.py"
    f.write_text(textwrap.dedent(source))
    return analyze_paths([str(f)], select=select)


RF011_BAD = """
    class SneakyAdvisor:
        def _propose(self):
            return {"lr": 0.1}

        def _feedback(self, score, knobs):
            self._X.append(knobs)
    """


def test_rf011_fires_on_unjournaled_hooks(tmp_path):
    r = _advisor_snippet(tmp_path, RF011_BAD)
    found = [f for f in r.unsuppressed if f.checker_id == "RF011"]
    assert len(found) == 2
    assert all(f.severity == "error" for f in found)
    assert "obs sweep" in found[0].message


def test_rf011_scoped_to_advisor_package_only(tmp_path):
    # The identical source OUTSIDE rafiki_tpu/advisor/ is legal: the
    # audit contract binds engines, not arbitrary code with _propose.
    r = _analyze_snippet(tmp_path, RF011_BAD)
    assert "RF011" not in _ids(r)


def test_rf011_quiet_when_hooks_journal(tmp_path):
    r = _advisor_snippet(tmp_path, """
        from rafiki_tpu.obs.search import audit

        class GoodAdvisor:
            def _propose(self):
                knobs = {"lr": 0.1}
                audit.record_propose(self, knobs, {"phase": "fixed"})
                return knobs

            def _propose_batch(self, n):
                out = [self._propose() for _ in range(n)]
                audit.record_propose_batch(self, n, out, strategy="seq")
                return out

            def _feedback(self, score, knobs):
                audit.record_feedback(self, score, knobs)
        """)
    assert "RF011" not in _ids(r)


def test_rf011_quiet_on_member_import_and_raw_journal(tmp_path):
    # Both alias shapes count: a member imported from audit, and the
    # journal handle itself.
    r = _advisor_snippet(tmp_path, """
        from rafiki_tpu.obs.journal import journal
        from rafiki_tpu.obs.search.audit import record_feedback

        class DirectAdvisor:
            def _propose(self):
                knobs = {"lr": 0.1}
                journal.record("advisor", "propose", knobs=knobs)
                return knobs

            def _feedback(self, score, knobs):
                record_feedback(self, score, knobs)
        """)
    assert "RF011" not in _ids(r)


def test_rf011_exempts_abstract_raise_only_hooks(tmp_path):
    # BaseAdvisor._propose's shape: a docstring plus a bare raise
    # decides nothing, so there is nothing to journal.
    r = _advisor_snippet(tmp_path, """
        class AbstractAdvisor:
            def _propose(self):
                \"\"\"Engines override.\"\"\"
                raise NotImplementedError
        """)
    assert "RF011" not in _ids(r)


def test_rf011_justified_suppression_honored(tmp_path):
    r = _advisor_snippet(tmp_path, """
        class ShimAdvisor:
            # lint: disable=RF011 — test shim, inner engine journals
            def _feedback(self, score, knobs):
                self.inner.feedback(score, knobs)
        """)
    assert "RF011" not in _ids(r)


def test_rf011_current_tree_is_clean():
    r = analyze_paths([os.path.join(REPO, "rafiki_tpu")], select=["RF011"])
    mine = [f for f in r.unsuppressed if f.checker_id == "RF011"]
    assert mine == [], [f"{f.path}:{f.line}" for f in mine]


# ---------------------------------------------------------------------------
# RF012 undamped-actuator
# ---------------------------------------------------------------------------


RF012_BAD = """
    def burst(lane, handle_cls):
        lane.scale_to(8)
        handle = handle_cls.ElasticHandle()
        handle.request(2)
    """


def test_rf012_fires_on_direct_actuator_calls(tmp_path):
    r = _analyze_snippet(tmp_path, RF012_BAD)
    found = [f for f in r.unsuppressed if f.checker_id == "RF012"]
    assert len(found) == 2
    assert all(f.severity == "error" for f in found)
    assert "AutoscaleController" in found[0].message


def test_rf012_exempts_autoscale_package(tmp_path):
    # The identical source INSIDE rafiki_tpu/autoscale/ is the surface
    # itself — the controller must be able to call its own actuators.
    pkg = tmp_path / "rafiki_tpu" / "autoscale"
    pkg.mkdir(parents=True)
    for d in (tmp_path / "rafiki_tpu", pkg):
        (d / "__init__.py").write_text("")
    f = pkg / "snippet.py"
    f.write_text(textwrap.dedent(RF012_BAD))
    r = analyze_paths([str(f)])
    assert "RF012" not in _ids(r)


def test_rf012_fires_on_lane_internals(tmp_path):
    r = _analyze_snippet(tmp_path, """
        def sneak(lane):
            lane._spawn_one()
            lane._drain_one()
        """)
    found = [f for f in r.unsuppressed if f.checker_id == "RF012"]
    assert len(found) == 2


def test_rf012_quiet_on_unrelated_request_calls(tmp_path):
    # .request on HTTP sessions / arbitrary objects is NOT the
    # actuator surface: only a name bound to ElasticHandle(...) is.
    r = _analyze_snippet(tmp_path, """
        import requests

        def fetch(session):
            session.request("GET", "/x")
            return requests.Session().request("GET", "/y")
        """)
    assert "RF012" not in _ids(r)


def test_rf012_tracks_elastic_handle_binding(tmp_path):
    r = _analyze_snippet(tmp_path, """
        from rafiki_tpu.scheduler.mesh import ElasticHandle

        def grow():
            h = ElasticHandle()
            h.request(1)
        """)
    found = [f for f in r.unsuppressed if f.checker_id == "RF012"]
    assert len(found) == 1
    assert "ElasticHandle" in found[0].message


def test_rf012_justified_suppression_honored(tmp_path):
    r = _analyze_snippet(tmp_path, """
        def teardown(lane):
            # lint: disable=RF012 — teardown after controller stop
            lane.scale_to(0)
        """)
    assert "RF012" not in _ids(r)


def test_rf012_current_tree_is_clean():
    r = analyze_paths([os.path.join(REPO, "rafiki_tpu"),
                       os.path.join(REPO, "scripts")], select=["RF012"])
    mine = [f for f in r.unsuppressed if f.checker_id == "RF012"]
    assert mine == [], [f"{f.path}:{f.line}" for f in mine]


# ---------------------------------------------------------------------------
# RF013 undurable-decision
# ---------------------------------------------------------------------------


def _scheduler_snippet(tmp_path, source, select=None):
    """Write the snippet INSIDE a rafiki_tpu/scheduler/ package tree so
    module_name_for resolves it into RF013's scope."""
    sched = tmp_path / "rafiki_tpu" / "scheduler"
    sched.mkdir(parents=True)
    for d in (tmp_path / "rafiki_tpu", sched):
        (d / "__init__.py").write_text("")
    f = sched / "snippet.py"
    f.write_text(textwrap.dedent(source))
    return analyze_paths([str(f)], select=select)


RF013_BAD = """
    def claim_and_assign(store, runner, knobs):
        trial = store.create_trial(knobs)
        runner.tasks.put(("pack", [trial]))
        runner.tasks.put(("resume", trial["id"]))
    """


def test_rf013_fires_on_undurable_mutations(tmp_path):
    r = _scheduler_snippet(tmp_path, RF013_BAD)
    found = [f for f in r.unsuppressed if f.checker_id == "RF013"]
    assert len(found) == 3
    assert all(f.severity == "error" for f in found)
    assert "unresumable" in found[0].message


def test_rf013_scoped_to_scheduler_package_only(tmp_path):
    # The identical source OUTSIDE rafiki_tpu/scheduler/ is legal: the
    # WAL contract binds the sweep control plane, not arbitrary code.
    r = _analyze_snippet(tmp_path, RF013_BAD)
    assert "RF013" not in _ids(r)


def test_rf013_quiet_when_intent_precedes(tmp_path):
    r = _scheduler_snippet(tmp_path, """
        def claim(store, wal, runner, knobs):
            txn = wal.intent("budget_claim", knobs_hash="h")
            trial = store.create_trial(knobs)
            wal.commit(txn, "budget_claim", trial_id=trial["id"])
            runner.tasks.put(("pack", [trial]))
        """)
    assert "RF013" not in _ids(r)


def test_rf013_guarded_wal_idiom_counts(tmp_path):
    # The degraded no-WAL mode: the intent call is conditionally
    # skipped at runtime but lexically present — recovery handles the
    # missing log loudly; the static contract is satisfied.
    r = _scheduler_snippet(tmp_path, """
        def backfill(store, wal, knobs):
            txn = None if wal is None else wal.intent("backfill")
            return store.create_trial(knobs)
        """)
    assert "RF013" not in _ids(r)


def test_rf013_mutation_before_intent_still_fires(tmp_path):
    # Ordering matters: an intent AFTER the mutation logs nothing the
    # reconciler can use for a crash in between.
    r = _scheduler_snippet(tmp_path, """
        def backwards(store, wal, knobs):
            trial = store.create_trial(knobs)
            wal.intent("budget_claim")
            return trial
        """)
    found = [f for f in r.unsuppressed if f.checker_id == "RF013"]
    assert len(found) == 1


def test_rf013_nested_closure_is_own_scope(tmp_path):
    # The enclosing function's intent does NOT cover a closure that
    # mutates later, on its own schedule: the closure needs its own.
    r = _scheduler_snippet(tmp_path, """
        def outer(store, wal, knobs):
            wal.intent("budget_claim")

            def backfill():
                return store.create_trial(knobs)
            return backfill
        """)
    found = [f for f in r.unsuppressed if f.checker_id == "RF013"]
    assert len(found) == 1


def test_rf013_ignores_non_assignment_puts(tmp_path):
    r = _scheduler_snippet(tmp_path, """
        def drain(runner, q):
            runner.tasks.put(("stop", None))
            q.put("anything")
        """)
    assert "RF013" not in _ids(r)


def test_rf013_justified_suppression_honored(tmp_path):
    r = _scheduler_snippet(tmp_path, """
        def fake_claim(store, knobs):
            # lint: disable=RF013 — test double; prod path WALs in mesh
            return store.create_trial(knobs)
        """)
    assert "RF013" not in _ids(r)


def test_rf013_current_scheduler_is_clean():
    r = analyze_paths([os.path.join(REPO, "rafiki_tpu")], select=["RF013"])
    mine = [f for f in r.unsuppressed if f.checker_id == "RF013"]
    assert mine == [], [f"{f.path}:{f.line}" for f in mine]


# ---------------------------------------------------------------------------
# RF014/RF016 — regression fixtures for the live violations this
# analysis surfaced when first enabled (fixed in bench.py,
# scripts/smoke_trial_pack.py, scripts/perf_smoke.py, and closed by the
# `obs decisions` reader). Each fixture freezes the *fixed* shape as
# quiet and the pre-fix shape as firing, so the fixes can't regress.
# ---------------------------------------------------------------------------


def _tree(tmp_path, files):
    import textwrap as _tw
    tmp_path.mkdir(parents=True, exist_ok=True)
    paths = []
    for name, src in files.items():
        f = tmp_path / name
        f.write_text(_tw.dedent(src))
        paths.append(str(f))
    return paths


def test_rf016_bench_trials_regression(tmp_path):
    # pre-fix bench.py: two reads of RAFIKI_BENCH_TRIALS with mode-
    # specific defaults "3"/"30" → divergent
    r = analyze_paths(_tree(tmp_path, {"bench_old.py": """
        import os
        def scale(mode):
            if mode == "cpu":
                return int(os.environ.get("RAFIKI_BENCH_TRIALS", "3"))
            return int(os.environ.get("RAFIKI_BENCH_TRIALS", "30"))
        """}), select=["RF016"])
    assert any("RAFIKI_BENCH_TRIALS" in f.message for f in r.unsuppressed)
    # the fix: one env read, mode-specific fallback in code
    r = analyze_paths(_tree(tmp_path / "fixed", {"bench_new.py": """
        import os
        def scale(mode):
            env_trials = os.environ.get("RAFIKI_BENCH_TRIALS")
            if mode == "cpu":
                return int(env_trials) if env_trials else 3
            return int(env_trials) if env_trials else 30
        """}), select=["RF016"])
    assert r.unsuppressed == []


def test_rf016_trial_pack_setdefault_regression(tmp_path):
    # pre-fix smoke scripts defaulted RAFIKI_TRIAL_PACK to "4" while
    # the worker defaults to "1" → divergent
    worker = """
        import os
        PACK = int(os.environ.get("RAFIKI_TRIAL_PACK", "1"))
        """
    r = analyze_paths(_tree(tmp_path, {"worker.py": worker,
                                       "smoke_old.py": """
        import os
        pack = max(2, int(os.environ.get("RAFIKI_TRIAL_PACK", "4")))
        """}), select=["RF016"])
    assert any("RAFIKI_TRIAL_PACK" in f.message for f in r.unsuppressed)
    # the fix: setdefault (a write, not a defaulted read) + required read
    r = analyze_paths(_tree(tmp_path / "fixed", {"worker.py": worker,
                                                 "smoke_new.py": """
        import os
        os.environ.setdefault("RAFIKI_TRIAL_PACK", "4")
        pack = max(2, int(os.environ["RAFIKI_TRIAL_PACK"]))
        """}), select=["RF016"])
    assert r.unsuppressed == []


def test_rf014_decisions_reader_closes_control_plane_records(tmp_path):
    # the four control-plane records were write-only until the
    # `obs decisions` CLI reader; its elif-chain shape must keep
    # counting as a reader for every branch
    writers = """
        def emit(journal):
            journal.record("serving", "route", reason="warm")
            journal.record("gateway", "shed", reason="capacity")
            journal.record("gateway", "breaker_transition", state="open")
            journal.record("twin", "placement", plan="p0")
        """
    r = analyze_paths(_tree(tmp_path, {"writers.py": writers}),
                      select=["RF014"])
    assert len(r.unsuppressed) == 4  # write-only: all four flagged
    r = analyze_paths(_tree(tmp_path / "fixed", {"writers.py": writers,
                                                 "decisions.py": """
        def decisions(recs):
            for r in recs:
                kind, name = r.get("kind"), r.get("name")
                if kind == "serving" and name == "route":
                    yield "route", r.get("reason")
                elif kind == "gateway" and name == "shed":
                    yield "shed", r.get("reason")
                elif kind == "gateway" and name == "breaker_transition":
                    yield "breaker", r.get("state")
                elif kind == "twin" and name == "placement":
                    yield "twin", r.get("plan")
        """}), select=["RF014"])
    assert r.unsuppressed == []


# ---------------------------------------------------------------------------
# RF017 unbounded-per-tenant-state
# ---------------------------------------------------------------------------


RF017_BAD = """
    from rafiki_tpu.tenancy import TenantFabric

    class Ledger:
        def __init__(self):
            self.stats = {}
            self.queues = {}

        def note(self, tenant_id, v):
            self.stats[tenant_id] = v
            self.queues.setdefault(tenant_id, []).append(v)
    """


def test_rf017_fires_on_tenant_keyed_writes(tmp_path):
    r = _analyze_snippet(tmp_path, RF017_BAD, select=["RF017"])
    found = [f for f in r.unsuppressed if f.checker_id == "RF017"]
    assert len(found) == 2  # the Store subscript AND the setdefault
    assert all("BoundedTenantMap" in f.message for f in found)


def test_rf017_scoped_to_tenancy_touching_modules(tmp_path):
    # The identical leak WITHOUT a rafiki_tpu.tenancy import is out of
    # scope: unbounded-keyed-state is only a wire-driven leak where
    # tenant ids actually flow.
    r = _analyze_snippet(tmp_path, RF017_BAD.replace(
        "from rafiki_tpu.tenancy import TenantFabric", "import os"),
        select=["RF017"])
    assert "RF017" not in _ids(r)


def test_rf017_quiet_with_eviction_or_cap(tmp_path):
    r = _analyze_snippet(tmp_path, """
        from rafiki_tpu.tenancy import TenantFabric

        class Pruned:
            def __init__(self):
                self.stats = {}

            def note(self, tenant_id, v):
                self.stats[tenant_id] = v
                while len(self.stats) > 64:
                    self.stats.pop(next(iter(self.stats)))
        """, select=["RF017"])
    assert "RF017" not in _ids(r)


def test_rf017_quiet_on_non_tenant_keys(tmp_path):
    r = _analyze_snippet(tmp_path, """
        from rafiki_tpu.tenancy import TenantFabric

        class ByReason:
            def __init__(self):
                self.shed = {}

            def note(self, reason):
                self.shed[reason] = self.shed.get(reason, 0) + 1
        """, select=["RF017"])
    assert "RF017" not in _ids(r)


def test_rf017_justified_suppression_honored(tmp_path):
    r = _analyze_snippet(tmp_path, """
        from rafiki_tpu.tenancy import TenantFabric

        class ConfigMap:
            def __init__(self, raw):
                self.tiers = {}
                for tenant, tier in raw.items():
                    # lint: disable=RF017 — construction-time config, not wire-keyed growth
                    self.tiers[tenant] = tier
        """, select=["RF017"])
    assert "RF017" not in _ids(r)


def test_rf017_current_tree_is_clean():
    r = analyze_paths([os.path.join(REPO, "rafiki_tpu"),
                       os.path.join(REPO, "bench.py"),
                       os.path.join(REPO, "scripts")], select=["RF017"])
    mine = [f for f in r.unsuppressed if f.checker_id == "RF017"]
    assert mine == [], [f"{f.path}:{f.line}" for f in mine]


# ---------------------------------------------------------------------------
# RF018 unaudited-speculation
# ---------------------------------------------------------------------------


RF018_BAD_MUTATION = """
    class LeakyAdvisor:
        def adopt_rows(self, rows):
            for x, y in rows:
                self._X.append(x)
                self._y.append(y)

        def drop_worst(self):
            del self._y[0]
    """


def test_rf018_fires_on_training_data_mutation_outside_surfaces(tmp_path):
    r = _advisor_snippet(tmp_path, RF018_BAD_MUTATION, select=["RF018"])
    found = [f for f in r.unsuppressed if f.checker_id == "RF018"]
    # append(x), append(y), del — three mutation sites
    assert len(found) == 3
    assert all(f.severity == "error" for f in found)
    assert "byte-identity" in found[0].message


def test_rf018_fires_on_unaudited_kill_site(tmp_path):
    r = _advisor_snippet(tmp_path, """
        class SilentKiller:
            def kill_verdict(self, h, epoch):
                st = self.trials[h]
                st.killed = True
                return st.fit
        """, select=["RF018"])
    found = [f for f in r.unsuppressed if f.checker_id == "RF018"]
    assert len(found) == 1
    assert "record_kill" in found[0].message


def test_rf018_scoped_to_advisor_package_only(tmp_path):
    # The identical source OUTSIDE rafiki_tpu/advisor/ is legal: the
    # contract binds the advisor package, not arbitrary code.
    r = _analyze_snippet(tmp_path, RF018_BAD_MUTATION, select=["RF018"])
    assert "RF018" not in _ids(r)


def test_rf018_quiet_on_sanctioned_surfaces_and_audited_kills(tmp_path):
    r = _advisor_snippet(tmp_path, """
        from rafiki_tpu.obs.search import audit

        class GoodAdvisor:
            def _feedback(self, score, knobs):
                self._X.append(knobs)
                self._y.append(score)
                audit.record_feedback(self, score, knobs)

            def _speculate(self, score, knobs):
                self._X.append(knobs)
                self._y.append(score)

            def _correct(self, score, knobs, predicted):
                self._y[0] = score
                audit.record_correct(self, knobs, predicted, score)

            def kill_verdict(self, h, epoch, best):
                st = self.trials[h]
                st.killed = True
                audit.record_kill(st.knobs, st.fit, epoch, best,
                                  config={}, trial_id=None)
                return st.fit
        """, select=["RF018"])
    assert "RF018" not in _ids(r)


def test_rf018_pure_kill_predicate_is_not_a_decision_site(tmp_path):
    # KillConfig.should_kill's shape: comparisons only, no state
    # mutated — a predicate, not a decision; the caller journals.
    r = _advisor_snippet(tmp_path, """
        class KillConfig:
            def should_kill(self, fit, epoch, best):
                return fit.hi < best - self.margin
        """, select=["RF018"])
    assert "RF018" not in _ids(r)


def test_rf018_justified_suppression_honored(tmp_path):
    r = _advisor_snippet(tmp_path, """
        class RebuildShim:
            def rebuild(self, rows):
                for x, y in rows:
                    # lint: disable=RF018 — rows come FROM advisor/feedback records, already journaled
                    self._X.append(x)
        """, select=["RF018"])
    assert "RF018" not in _ids(r)


def test_rf018_current_tree_is_clean():
    r = analyze_paths([os.path.join(REPO, "rafiki_tpu")], select=["RF018"])
    mine = [f for f in r.unsuppressed if f.checker_id == "RF018"]
    assert mine == [], [f"{f.path}:{f.line}" for f in mine]


# ---------------------------------------------------------------------------
# RF019 full-gather-hazard
# ---------------------------------------------------------------------------


RF019_BAD_GATHER = """
    import jax
    import numpy as np
    from rafiki_tpu.shard import ShardedTrainLoop, train_sharded

    def snapshot(model, uri, devices):
        loop, history = train_sharded(model, uri, devices)
        host = jax.device_get(loop.state)
        return np.asarray(host), history

    def peek(init_fn, apply_fn, loss_fn, devices):
        loop = ShardedTrainLoop(init_fn, apply_fn, loss_fn,
                                devices=devices)
        st = loop.state
        return np.asarray(st)
    """


def test_rf019_fires_on_full_gather_of_group_state(tmp_path):
    r = _analyze_snippet(tmp_path, RF019_BAD_GATHER, select=["RF019"])
    found = [f for f in r.unsuppressed if f.checker_id == "RF019"]
    # device_get(loop.state), np.asarray(host)... host is not tracked
    # (one-hop chains only) — device_get + np.asarray(st) = 2 sites
    assert len(found) == 2
    assert all(f.severity == "error" for f in found)
    assert "gather_state" in found[0].message


def test_rf019_quiet_on_sanctioned_paths(tmp_path):
    # save_sharded of loop.state and gather_state are THE manifest
    # path; device_get of anything untainted is ordinary jax.
    r = _analyze_snippet(tmp_path, """
        import jax
        from rafiki_tpu.shard import (gather_state, save_sharded,
                                      train_sharded)

        def checkpoint(store, tid, model, uri, devices):
            loop, _hist = train_sharded(model, uri, devices)
            save_sharded(store, tid, 0, loop.state, loop.width)
            return gather_state(loop.state)

        def other(x):
            return jax.device_get(x)
        """, select=["RF019"])
    assert "RF019" not in _ids(r)


def test_rf019_exempts_the_checkpoint_module_itself(tmp_path):
    shard = tmp_path / "rafiki_tpu" / "shard"
    shard.mkdir(parents=True)
    for d in (tmp_path / "rafiki_tpu", shard):
        (d / "__init__.py").write_text("")
    f = shard / "checkpoint.py"
    f.write_text(textwrap.dedent(RF019_BAD_GATHER))
    r = analyze_paths([str(f)], select=["RF019"])
    assert "RF019" not in _ids(r)


def test_rf019_justified_suppression_honored(tmp_path):
    r = _analyze_snippet(tmp_path, """
        import numpy as np
        from rafiki_tpu.shard import train_sharded

        def debug_norms(model, uri, devices):
            loop, _h = train_sharded(model, uri, devices)
            # lint: disable=RF019 — scalar leaf norms only, bounded copy
            return np.asarray(loop.state)
        """, select=["RF019"])
    assert "RF019" not in _ids(r)


def test_rf019_current_tree_is_clean():
    r = analyze_paths([os.path.join(REPO, "rafiki_tpu"),
                       os.path.join(REPO, "bench.py"),
                       os.path.join(REPO, "scripts")], select=["RF019"])
    mine = [f for f in r.unsuppressed if f.checker_id == "RF019"]
    assert mine == [], [f"{f.path}:{f.line}" for f in mine]
