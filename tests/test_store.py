import threading

import pytest

from rafiki_tpu.constants import TrainJobStatus, TrialStatus
from rafiki_tpu.store import MetaStore, ParamsStore


@pytest.fixture()
def store(tmp_path):
    return MetaStore(tmp_path / "meta.sqlite3")


def test_users(store):
    u = store.create_user("a@b.c", "hash", "ADMIN")
    assert store.get_user_by_email("a@b.c")["id"] == u["id"]
    store.ban_user(u["id"])
    assert store.get_user(u["id"])["banned"] == 1


def test_models(store):
    m = store.create_model("ff", "IMAGE_CLASSIFICATION", None, b"code", "FF",
                           dependencies={"flax": "*"})
    got = store.get_model_by_name("ff")
    assert got["model_file"] == b"code"
    assert got["dependencies"] == {"flax": "*"}
    assert store.get_models_of_task("IMAGE_CLASSIFICATION")[0]["id"] == m["id"]
    assert store.get_models_of_task("POS_TAGGING") == []


def test_train_job_versioning(store):
    j1 = store.create_train_job("app", "T", None, "u1", "u2", {"MODEL_TRIAL_COUNT": 3})
    j2 = store.create_train_job("app", "T", None, "u1", "u2", {"MODEL_TRIAL_COUNT": 3})
    assert (j1["app_version"], j2["app_version"]) == (1, 2)
    assert store.get_train_job_by_app("app")["id"] == j2["id"]
    assert store.get_train_job_by_app("app", app_version=1)["id"] == j1["id"]
    assert j1["budget"] == {"MODEL_TRIAL_COUNT": 3}


def test_trial_lifecycle_and_best(store):
    j = store.create_train_job("app", "T", None, "u1", "u2", {})
    s = store.create_sub_train_job(j["id"], "model1")
    scores = [0.5, 0.9, 0.7, None]
    for i, sc in enumerate(scores):
        t = store.create_trial(s["id"], "ff", {"lr": i}, worker_id=f"w{i}")
        assert t["no"] == i + 1
        if sc is None:
            store.mark_trial_as_errored(t["id"], "boom")
        else:
            store.mark_trial_as_completed(t["id"], sc, params_id=f"p{i}")
    best = store.get_best_trials_of_train_job(j["id"], limit=2)
    assert [b["score"] for b in best] == [0.9, 0.7]
    assert best[0]["params_id"] == "p1"
    assert store.count_trials_of_sub_train_job(s["id"]) == 4
    assert store.count_trials_of_sub_train_job(
        s["id"], statuses=[TrialStatus.ERRORED.value]) == 1
    trials = store.get_trials_of_train_job(j["id"])
    assert len(trials) == 4 and trials[0]["knobs"] == {"lr": 0}


def test_trial_logs(store):
    j = store.create_train_job("app", "T", None, "u", "v", {})
    s = store.create_sub_train_job(j["id"], "m")
    t = store.create_trial(s["id"], "ff", {})
    store.add_trial_log(t["id"], {"type": "values", "values": {"loss": 0.5}, "time": 1.0})
    store.add_trial_log(t["id"], {"type": "message", "message": "hi", "time": 2.0})
    logs = store.get_trial_logs(t["id"])
    assert len(logs) == 2 and logs[0]["values"]["loss"] == 0.5


def test_concurrent_writes(store, tmp_path):
    j = store.create_train_job("app", "T", None, "u", "v", {})
    s = store.create_sub_train_job(j["id"], "m")

    def worker(i):
        # every thread gets its own connection via threading.local
        t = store.create_trial(s["id"], "ff", {"i": i}, worker_id=f"w{i}")
        store.mark_trial_as_completed(t["id"], i / 10, params_id=None)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    trials = store.get_trials_of_sub_train_job(s["id"])
    assert len(trials) == 8
    assert sorted(t["no"] for t in trials) == list(range(1, 9)) or len({t["id"] for t in trials}) == 8


def test_inference_jobs_and_services(store):
    j = store.create_train_job("app", "T", None, "u", "v", {})
    i = store.create_inference_job(j["id"], None)
    store.update_inference_job(i["id"], status="RUNNING", predictor_host="127.0.0.1:30000")
    got = store.get_inference_job_of_train_job(j["id"])
    assert got["predictor_host"] == "127.0.0.1:30000"
    s = store.create_service("TRAIN_WORKER", job_id=j["id"], worker_index=0, devices=["tpu:0"])
    store.update_service(s["id"], status="RUNNING", heartbeat=True)
    assert store.get_services_of_job(j["id"])[0]["status"] == "RUNNING"


def test_params_store_round_trip(tmp_path):
    ps = ParamsStore(tmp_path / "params")
    pid = ps.save(b"weights-blob")
    assert ps.load(pid) == b"weights-blob"
    assert ps.exists(pid)
    assert pid in ps.list()
    ps.delete(pid)
    assert not ps.exists(pid)


def test_params_store_integrity(tmp_path):
    ps = ParamsStore(tmp_path / "params")
    pid = ps.save(b"data")
    # corrupt the file
    path = ps._path(pid)
    raw = path.read_bytes()
    path.write_bytes(raw[:-1] + b"X")
    with pytest.raises(IOError):
        ps.load(pid)


def test_params_store_checkpoints(tmp_path):
    ps = ParamsStore(tmp_path / "params")
    ps.save_checkpoint("trial1", 10, b"s10")
    ps.save_checkpoint("trial1", 20, b"s20")
    step, blob = ps.latest_checkpoint("trial1")
    assert (step, blob) == (20, b"s20")
    ps.delete_checkpoints("trial1")
    assert ps.latest_checkpoint("trial1") is None


def test_params_store_rejects_traversal(tmp_path):
    ps = ParamsStore(tmp_path / "params")
    with pytest.raises(ValueError):
        ps.load("../etc/passwd")
