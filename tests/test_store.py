import threading

import pytest

from rafiki_tpu.constants import TrainJobStatus, TrialStatus
from rafiki_tpu.store import MetaStore, ParamsStore


@pytest.fixture()
def store(tmp_path):
    return MetaStore(tmp_path / "meta.sqlite3")


def test_users(store):
    u = store.create_user("a@b.c", "hash", "ADMIN")
    assert store.get_user_by_email("a@b.c")["id"] == u["id"]
    store.ban_user(u["id"])
    assert store.get_user(u["id"])["banned"] == 1


def test_models(store):
    m = store.create_model("ff", "IMAGE_CLASSIFICATION", None, b"code", "FF",
                           dependencies={"flax": "*"})
    got = store.get_model_by_name("ff")
    assert got["model_file"] == b"code"
    assert got["dependencies"] == {"flax": "*"}
    assert store.get_models_of_task("IMAGE_CLASSIFICATION")[0]["id"] == m["id"]
    assert store.get_models_of_task("POS_TAGGING") == []


def test_train_job_versioning(store):
    j1 = store.create_train_job("app", "T", None, "u1", "u2", {"MODEL_TRIAL_COUNT": 3})
    j2 = store.create_train_job("app", "T", None, "u1", "u2", {"MODEL_TRIAL_COUNT": 3})
    assert (j1["app_version"], j2["app_version"]) == (1, 2)
    assert store.get_train_job_by_app("app")["id"] == j2["id"]
    assert store.get_train_job_by_app("app", app_version=1)["id"] == j1["id"]
    assert j1["budget"] == {"MODEL_TRIAL_COUNT": 3}


def test_trial_lifecycle_and_best(store):
    j = store.create_train_job("app", "T", None, "u1", "u2", {})
    s = store.create_sub_train_job(j["id"], "model1")
    scores = [0.5, 0.9, 0.7, None]
    for i, sc in enumerate(scores):
        t = store.create_trial(s["id"], "ff", {"lr": i}, worker_id=f"w{i}")
        assert t["no"] == i + 1
        if sc is None:
            store.mark_trial_as_errored(t["id"], "boom")
        else:
            store.mark_trial_as_completed(t["id"], sc, params_id=f"p{i}")
    best = store.get_best_trials_of_train_job(j["id"], limit=2)
    assert [b["score"] for b in best] == [0.9, 0.7]
    assert best[0]["params_id"] == "p1"
    assert store.count_trials_of_sub_train_job(s["id"]) == 4
    assert store.count_trials_of_sub_train_job(
        s["id"], statuses=[TrialStatus.ERRORED.value]) == 1
    trials = store.get_trials_of_train_job(j["id"])
    assert len(trials) == 4 and trials[0]["knobs"] == {"lr": 0}


def test_trial_logs(store):
    j = store.create_train_job("app", "T", None, "u", "v", {})
    s = store.create_sub_train_job(j["id"], "m")
    t = store.create_trial(s["id"], "ff", {})
    store.add_trial_log(t["id"], {"type": "values", "values": {"loss": 0.5}, "time": 1.0})
    store.add_trial_log(t["id"], {"type": "message", "message": "hi", "time": 2.0})
    logs = store.get_trial_logs(t["id"])
    assert len(logs) == 2 and logs[0]["values"]["loss"] == 0.5


def test_concurrent_writes(store, tmp_path):
    j = store.create_train_job("app", "T", None, "u", "v", {})
    s = store.create_sub_train_job(j["id"], "m")

    def worker(i):
        # every thread gets its own connection via threading.local
        t = store.create_trial(s["id"], "ff", {"i": i}, worker_id=f"w{i}")
        store.mark_trial_as_completed(t["id"], i / 10, params_id=None)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    trials = store.get_trials_of_sub_train_job(s["id"])
    assert len(trials) == 8
    assert sorted(t["no"] for t in trials) == list(range(1, 9)) or len({t["id"] for t in trials}) == 8


def test_inference_jobs_and_services(store):
    j = store.create_train_job("app", "T", None, "u", "v", {})
    i = store.create_inference_job(j["id"], None)
    store.update_inference_job(i["id"], status="RUNNING", predictor_host="127.0.0.1:30000")
    got = store.get_inference_job_of_train_job(j["id"])
    assert got["predictor_host"] == "127.0.0.1:30000"
    s = store.create_service("TRAIN_WORKER", job_id=j["id"], worker_index=0, devices=["tpu:0"])
    store.update_service(s["id"], status="RUNNING", heartbeat=True)
    assert store.get_services_of_job(j["id"])[0]["status"] == "RUNNING"


def test_params_store_round_trip(tmp_path):
    ps = ParamsStore(tmp_path / "params")
    pid = ps.save(b"weights-blob")
    assert ps.load(pid) == b"weights-blob"
    assert ps.exists(pid)
    assert pid in ps.list()
    ps.delete(pid)
    assert not ps.exists(pid)


def test_params_store_integrity(tmp_path):
    ps = ParamsStore(tmp_path / "params")
    pid = ps.save(b"data")
    # corrupt the file
    path = ps._path(pid)
    raw = path.read_bytes()
    path.write_bytes(raw[:-1] + b"X")
    with pytest.raises(IOError):
        ps.load(pid)


def test_params_store_checkpoints(tmp_path):
    ps = ParamsStore(tmp_path / "params")
    ps.save_checkpoint("trial1", 10, b"s10")
    ps.save_checkpoint("trial1", 20, b"s20")
    step, blob = ps.latest_checkpoint("trial1")
    assert (step, blob) == (20, b"s20")
    ps.delete_checkpoints("trial1")
    assert ps.latest_checkpoint("trial1") is None


def test_params_store_rejects_traversal(tmp_path):
    ps = ParamsStore(tmp_path / "params")
    with pytest.raises(ValueError):
        ps.load("../etc/passwd")


# ---------------------------------------------------------------------------
# Content-addressed params store (docs/autoscale.md): same contract as
# the plain store, chunk-level dedup underneath.
# ---------------------------------------------------------------------------

from rafiki_tpu.store import CasParamsStore, make_params_store  # noqa: E402


def test_cas_store_round_trip(tmp_path):
    ps = CasParamsStore(tmp_path / "params")
    blob = bytes(range(256)) * 1024  # 256 KB, multiple chunks
    pid = ps.save(blob)
    assert ps.load(pid) == blob
    assert ps.exists(pid)
    assert pid in ps.list()
    ps.delete(pid)
    assert not ps.exists(pid)


def test_cas_store_reads_plain_format_in_place(tmp_path):
    """Flipping RAFIKI_PARAMS_CAS on over an existing directory must
    not strand old checkpoints: the CAS store reads plain files."""
    plain = ParamsStore(tmp_path / "params")
    pid = plain.save(b"pre-cas-weights")
    cas = CasParamsStore(tmp_path / "params")
    assert cas.load(pid) == b"pre-cas-weights"
    # and the plain path still integrity-checks
    path = cas._path(pid)
    path.write_bytes(path.read_bytes()[:-1] + b"X")
    with pytest.raises(IOError):
        cas.load(pid)


def test_cas_second_write_dedups(tmp_path):
    """The acceptance number: a second checkpoint of a near-identical
    tree writes < 20% of the first's bytes."""
    import hashlib as _hashlib

    ps = CasParamsStore(tmp_path / "params")
    # 1 MB of DISTINCT chunk content (a repeating pattern would dedup
    # against itself on the first write and prove nothing).
    base = bytearray(b"".join(
        _hashlib.sha256(str(i).encode()).digest() for i in range(32768)))
    first = bytes(base)
    base[100] ^= 0xFF  # one flipped byte = one dirty chunk
    second = bytes(base)
    ps.save(first)
    w0 = ps.stats()["bytes_written"]
    pid2 = ps.save(second)
    w1 = ps.stats()["bytes_written"] - w0
    assert w1 < 0.2 * w0, f"second write {w1}B vs first {w0}B"
    assert ps.load(pid2) == second
    assert ps.stats()["dedup_ratio"] > 0.4


def test_cas_identical_write_is_all_hits(tmp_path):
    ps = CasParamsStore(tmp_path / "params")
    blob = b"z" * (200 * 1024)
    p1 = ps.save(blob)
    w0 = ps.stats()["bytes_written"]
    p2 = ps.save(blob)
    # only the (tiny) manifest is new
    assert ps.stats()["bytes_written"] - w0 < 2048
    assert p1 != p2 and ps.load(p1) == ps.load(p2) == blob


def test_cas_missing_and_corrupt_chunks_fail_integrity(tmp_path):
    ps = CasParamsStore(tmp_path / "params")
    blob = bytes(range(256)) * 1024
    pid = ps.save(blob)
    chunks = sorted(p for p in (tmp_path / "params" / "chunks").iterdir()
                    if p.suffix != ".tmp")
    victim = chunks[0]
    saved = victim.read_bytes()
    victim.write_bytes(saved[:-1] + b"X")
    with pytest.raises(IOError, match="corrupt"):
        ps.load(pid)
    victim.unlink()
    with pytest.raises(IOError, match="missing chunk"):
        ps.load(pid)


def test_cas_gc_keeps_live_chunks(tmp_path):
    ps = CasParamsStore(tmp_path / "params")
    keep = ps.save(b"a" * (128 * 1024))
    drop = ps.save(b"b" * (128 * 1024))
    ps.delete(drop)
    removed = ps.gc()
    assert removed >= 1
    assert ps.load(keep) == b"a" * (128 * 1024)  # survivors intact
    assert ps.gc() == 0  # idempotent


def test_make_params_store_honours_env(tmp_path, monkeypatch):
    monkeypatch.delenv("RAFIKI_PARAMS_CAS", raising=False)
    assert type(make_params_store(tmp_path / "p1")) is ParamsStore
    monkeypatch.setenv("RAFIKI_PARAMS_CAS", "1")
    assert isinstance(make_params_store(tmp_path / "p2"), CasParamsStore)
