"""Whole-program contract extraction + RF014–RF016, proven in both
polarities.

The extractors are tested on synthetic module trees (no filesystem),
the checkers through ``analyze_paths`` over fixture trees on disk —
including the doctored rename of ``mesh/pack_formed`` the acceptance
criteria name: renaming EITHER the writer or the reader side must
fail loudly, naming the kind and both sites. Dynamic shapes
(non-constant kinds, ``**kwargs`` field sets, computed env defaults)
must degrade to manifest-visible warnings, never false errors.
"""

import ast
import json
import os
import textwrap

from rafiki_tpu.analysis import analyze_paths, load_builtin_checkers
from rafiki_tpu.analysis.contracts.envknobs import extract_env
from rafiki_tpu.analysis.contracts.journal import (
    extract_journal, missing_reader_fields, unknown_reader_keys,
    unread_writer_keys)
from rafiki_tpu.analysis.contracts.manifest import (
    build_manifest, dump_manifest, manifest_for_paths)
from rafiki_tpu.analysis.contracts.telem import (
    documented_names, extract_telemetry, is_documented, join_prom_golden)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

load_builtin_checkers()


class _Mod:
    def __init__(self, path, src):
        self.path = path
        self.tree = ast.parse(textwrap.dedent(src))


def _mods(**files):
    return [_Mod(p.replace("__", "/") + ".py", s)
            for p, s in files.items()]


def _write_tree(tmp_path, files):
    paths = []
    tmp_path.mkdir(parents=True, exist_ok=True)
    for name, src in files.items():
        f = tmp_path / name
        f.write_text(textwrap.dedent(src))
        paths.append(str(f))
    return paths


def _unsup(result, checker=None):
    return [f for f in result.unsuppressed
            if checker is None or f.checker_id == checker]


# ---------------------------------------------------------------------------
# journal extraction
# ---------------------------------------------------------------------------


def test_writer_extraction_constants_and_fields():
    jc = extract_journal(_mods(w="""
        KIND = "advisor"
        def go(journal, advisor):
            journal.record("mesh", "pack_formed", chip=0, k=4)
            journal.record(KIND, "propose", knobs={}, **_ident(advisor))
        """))
    pairs = jc.writer_pairs()
    assert pairs["mesh/pack_formed"][0].fields == ("chip", "k")
    assert not pairs["mesh/pack_formed"][0].dynamic_fields
    # module-constant kind resolves; **kwargs marks the set open
    assert pairs["advisor/propose"][0].dynamic_fields
    assert jc.fields_written("advisor", "propose") is None
    assert "chip" in jc.fields_written("mesh", "pack_formed")


def test_dynamic_kind_degrades_to_manifest_warning_not_error():
    jc = extract_journal(_mods(w="""
        def go(journal, kind):
            journal.record(kind, "x", a=1)
        """))
    assert not jc.writers
    assert len(jc.dynamic_writers) == 1
    # and a constant kind with a dynamic name is a wildcard writer
    jc2 = extract_journal(_mods(w="""
        def go(journal, ev):
            journal.record("event", ev, a=1)
        """))
    assert jc2.writer_pairs().keys() == {"event/*"}
    assert jc2.wildcard_kinds() == {"event"}


def test_reader_filter_guard_alias_and_projection():
    jc = extract_journal(_mods(r="""
        FIELDS = ("chip", "packing_key")
        def read(recs):
            out = []
            for r in recs:
                if r.get("kind") != "mesh":
                    continue
                kind, name = r.get("kind"), r.get("name")
                if name == "pack_formed":
                    out.append({f: r.get(f) for f in ("chip", "fill_ratio")})
            return out
        """))
    pairs = jc.reader_pairs()
    # the guard-continue flips to a positive kind constraint...
    assert "mesh/*" in pairs
    # ...and the alias comparison refines it to the pair, with the
    # projection idiom's looped constant fields attached
    site = pairs["mesh/pack_formed"][0]
    assert site.fields == ["chip", "fill_ratio"]


def test_reader_required_kinds_and_membership():
    jc = extract_journal(_mods(r="""
        REQUIRED_KINDS = ("perf/step", "mesh/pack_formed")
        def scan(recs):
            return [r for r in recs
                    if r.get("kind") == "mesh"
                    and r.get("name") in ("repack", "chip_lost")]
        """))
    pairs = jc.reader_pairs()
    assert {"perf/step", "mesh/pack_formed"} <= set(pairs)
    assert pairs["perf/step"][0].source == "required-kinds"
    assert {"mesh/repack", "mesh/chip_lost"} <= set(pairs)


def test_helper_predicate_call_sites_become_readers():
    jc = extract_journal(_mods(r="""
        def _has(recs, kind, name):
            return any(r.get("kind") == kind and r.get("name") == name
                       for r in recs)
        def check(recs):
            assert _has(recs, "mesh", "repack")
            assert _has(recs, "recovery", "rehydrated")
        """))
    pairs = jc.reader_pairs()
    assert pairs["mesh/repack"][0].source == "helper-call"
    assert "recovery/rehydrated" in pairs


def test_joins_unread_unknown_and_missing_fields():
    jc = extract_journal(_mods(w="""
        def go(journal):
            journal.record("mesh", "pack_formed", chip=0)
            journal.record("orphan", "write_only", a=1)
        """, r="""
        def read(recs):
            for r in recs:
                if r.get("kind") == "mesh" and r.get("name") == "pack_formed":
                    print(r.get("chip"), r.get("fill_ratio"))
                if r.get("kind") == "ghost":
                    pass
        """))
    assert unread_writer_keys(jc) == ["orphan/write_only"]
    assert unknown_reader_keys(jc) == ["ghost/*"]
    [(site, missing)] = missing_reader_fields(jc)
    assert site.key == "mesh/pack_formed" and missing == ["fill_ratio"]


# ---------------------------------------------------------------------------
# env-knob extraction
# ---------------------------------------------------------------------------


def test_env_read_shapes_defaults_and_parse_types():
    env = extract_env(_mods(m="""
        import os
        from pathlib import Path
        ENV_VAR = "RAFIKI_INDIRECT"
        a = int(os.environ.get("RAFIKI_A", "3"))
        b = os.environ["RAFIKI_B"]
        c = float(os.getenv("RAFIKI_C", "0.5"))
        d = Path(os.environ.get("RAFIKI_D", "~/x"))
        e = os.environ.get("RAFIKI_E", "0").lower() in ("1", "true")
        f = os.environ.get("RAFIKI_F", f"pw-{os.getpid()}")
        g = os.environ.get(ENV_VAR, "")
        """))
    by = env.by_knob()
    assert by["RAFIKI_A"][0].parse == "int"
    assert by["RAFIKI_A"][0].manifest_default() == "'3'"
    assert by["RAFIKI_B"][0].required
    assert by["RAFIKI_B"][0].manifest_default() == "<required>"
    assert by["RAFIKI_C"][0].parse == "float"
    assert by["RAFIKI_D"][0].parse == "path"
    assert by["RAFIKI_E"][0].parse == "flag"
    assert by["RAFIKI_F"][0].dynamic_default
    assert by["RAFIKI_F"][0].manifest_default() == "<dynamic>"
    assert "RAFIKI_INDIRECT" in by  # ENV_VAR-constant indirection


def test_env_helper_wrapped_reads_resolved_at_call_sites():
    # autoscale/health shape: module-private helpers hide the environ
    # read behind a parameter (with or without prefix concatenation);
    # constant-argument call sites must still land in the registry
    env = extract_env(_mods(m="""
        import os
        ENV_PREFIX = "RAFIKI_AS_"
        ENV_K = "RAFIKI_H_K"
        def _env_float(name, default):
            raw = os.environ.get(ENV_PREFIX + name)
            return default if raw is None else float(raw)
        def _full(name, default):
            try:
                return float(os.environ.get(name, "") or default)
            except ValueError:
                return default
        def _on(name):
            return os.environ.get(name, "1").lower() not in ("0", "off")
        def build(tick):
            a = _env_float("TICK_S", 1.0)
            b = _full(ENV_K, 50.0)
            c = _on("RAFIKI_H")
            d = _env_float(tick, 2.0)   # dynamic name: degrades silently
        """))
    by = env.by_knob()
    assert by["RAFIKI_AS_TICK_S"][0].parse == "float"
    assert by["RAFIKI_AS_TICK_S"][0].manifest_default() == "1.0"
    assert by["RAFIKI_H_K"][0].manifest_default() == "50.0"
    assert by["RAFIKI_H"][0].parse == "flag"
    assert by["RAFIKI_H"][0].manifest_default() == "'1'"  # helper-internal
    assert len(env.reads) == 3


def test_env_divergence_only_on_distinct_constant_defaults():
    env = extract_env(_mods(a="""
        import os
        x = os.environ.get("RAFIKI_K", "1")
        y = os.environ.get("RAFIKI_R", "5")
        """, b="""
        import os
        x = os.environ.get("RAFIKI_K", "4")
        y = os.environ.get("RAFIKI_R", "5")
        z = os.environ["RAFIKI_K"]          # required: can't diverge
        w = os.environ.get("RAFIKI_R", f"{1}")  # dynamic: can't diverge
        """))
    assert set(env.divergent()) == {"RAFIKI_K"}


def test_spawn_provenance_inherit_vs_explicit():
    env = extract_env(_mods(s="""
        import os, subprocess, sys
        def good():
            env = dict(os.environ)
            env["RAFIKI_EXTRA"] = "1"
            subprocess.Popen([sys.executable, "-m", "child"], env=env)
        def bad():
            env = {"PATH": "/bin", "RAFIKI_ONLY": "1"}
            subprocess.Popen([sys.executable, "-m", "child"], env=env)
        def bare():
            subprocess.run([sys.executable, "-m", "child"])
        """))
    good, bad, bare = sorted(env.spawns, key=lambda s: s.line)
    assert good.inherits_environ
    assert not bad.inherits_environ
    assert bad.explicit_keys == ("PATH", "RAFIKI_ONLY")
    assert bare.inherits_environ  # no env kwarg: child inherits


# ---------------------------------------------------------------------------
# telemetry extraction + joins
# ---------------------------------------------------------------------------


def test_telemetry_sites_dynamic_prefixes_and_collectors():
    tc = extract_telemetry(_mods(t="""
        def go(telemetry, reason, cold):
            telemetry.inc("gateway.admitted")
            telemetry.observe("train.cold_epoch_s" if cold
                              else "train.epoch_s", 1.0)
            telemetry.inc(f"gateway.shed_{reason}")
            telemetry.register_collector("goodput", lambda: {})
        """))
    names = tc.names()
    assert {"gateway.admitted", "train.cold_epoch_s",
            "train.epoch_s"} <= set(names)
    assert tc.dynamic_sites[0].prefix == "gateway.shed_"
    assert [c.name for c in tc.collectors] == ["goodput"]


def test_documented_names_brace_shorthand_and_wildcards():
    exact, wild = documented_names(textwrap.dedent("""\
        prose with `not.a.metric` backticks is ignored
        | Name | Kind | Meaning |
        |---|---|---|
        | `program_cache.{hits,misses,evictions}` | counter | x |
        | `gateway.breaker_opened` / `_half_open` / `_closed` | counter | x |
        | `trial_pack.total` / `.build` | span | x |
        | `chaos.injected` (+ `chaos.injected.<site>.<mode>`) | counter | x |
        """))
    assert {"program_cache.hits", "program_cache.misses",
            "program_cache.evictions"} <= exact
    # shorthand resolves against the row's first FULL name
    assert {"gateway.breaker_half_open", "gateway.breaker_closed"} <= exact
    assert "trial_pack.build" in exact
    assert "not.a.metric" not in exact
    assert is_documented("chaos.injected.train_epoch.delay", exact, wild)
    assert not is_documented("chaos.other", exact, wild)


def test_join_prom_golden_classification():
    tc = extract_telemetry(_mods(t="""
        def go(telemetry, reason):
            telemetry.observe("train.epoch_s", 1.0)
            telemetry.inc(f"gateway.shed_{reason}")
            telemetry.register_collector("goodput", lambda: {})
        """))
    got = join_prom_golden(textwrap.dedent("""\
        # TYPE rafiki_train_epoch_s summary
        # TYPE rafiki_goodput_goodput gauge
        # TYPE rafiki_span_trial_total summary
        # TYPE rafiki_gateway_shed_capacity counter
        # TYPE rafiki_orphan_metric counter
        """), tc)
    assert got["matched"] == ["train_epoch_s"]
    assert set(got["explained"]) == {"goodput_goodput", "span_trial_total",
                                     "gateway_shed_capacity"}
    assert got["unexplained"] == ["orphan_metric"]


# ---------------------------------------------------------------------------
# manifest determinism
# ---------------------------------------------------------------------------


def test_manifest_byte_deterministic_across_runs():
    paths = [os.path.join(REPO, "rafiki_tpu"),
             os.path.join(REPO, "bench.py"), os.path.join(REPO, "scripts")]
    a = dump_manifest(manifest_for_paths(paths, root=REPO))
    b = dump_manifest(manifest_for_paths(paths, root=REPO))
    assert a == b
    m = json.loads(a)
    assert m["version"] == 1
    # repo-relative paths with forward slashes, however invoked
    site = next(iter(m["env"]["knobs"].values()))["sites"][0]
    assert not os.path.isabs(site) and "\\" not in site


def test_build_manifest_is_pure_and_stable_on_synthetic_tree():
    files = dict(w="""
        def go(journal):
            journal.record("mesh", "pack_formed", chip=0)
        """)
    a = dump_manifest(build_manifest(_mods(**files)))
    b = dump_manifest(build_manifest(_mods(**files)))  # fresh ASTs
    assert a == b


# ---------------------------------------------------------------------------
# RF014 — both polarities, including the doctored rename
# ---------------------------------------------------------------------------

_FIXTURE_WRITER = """
    def form_pack(journal):
        journal.record("mesh", "pack_formed", chip=0, k=4,
                       fill_ratio=1.0)
"""
_FIXTURE_READER = """
    REQUIRED_KINDS = ("mesh/pack_formed",)
    def calibrate(recs):
        for r in recs:
            if r.get("kind") == "mesh" and r.get("name") == "pack_formed":
                yield r.get("fill_ratio")
"""


def test_rf014_quiet_on_matched_fixture(tmp_path):
    paths = _write_tree(tmp_path, {"writer.py": _FIXTURE_WRITER,
                                   "reader.py": _FIXTURE_READER})
    assert _unsup(analyze_paths(paths, select=["RF014"])) == []


def test_rf014_catches_writer_side_rename_naming_both_sites(tmp_path):
    doctored = _FIXTURE_WRITER.replace("pack_formed", "pack_formedx")
    paths = _write_tree(tmp_path, {"writer.py": doctored,
                                   "reader.py": _FIXTURE_READER})
    found = _unsup(analyze_paths(paths, select=["RF014"]))
    errors = [f for f in found if f.severity == "error"]
    assert errors, "reader-side dangling expectation must be an error"
    msg = errors[0].message
    assert "mesh/pack_formed" in msg            # the kind, by name
    assert "writer.py" in msg and "renamed?" in msg  # the other site
    assert errors[0].path.endswith("reader.py")      # this site
    # and the renamed writer is now unread (warning polarity)
    assert any(f.severity == "warning" and f.path.endswith("writer.py")
               for f in found)


def test_rf014_catches_reader_side_rename_naming_both_sites(tmp_path):
    doctored = _FIXTURE_READER.replace("pack_formed", "pack_formedx")
    paths = _write_tree(tmp_path, {"writer.py": _FIXTURE_WRITER,
                                   "reader.py": doctored})
    found = _unsup(analyze_paths(paths, select=["RF014"]))
    errors = [f for f in found if f.severity == "error"]
    assert errors and errors[0].path.endswith("reader.py")
    assert "mesh/pack_formedx" in errors[0].message
    assert "mesh/pack_formed" in errors[0].message  # closest-match hint
    assert "writer.py" in errors[0].message


def test_rf014_unread_writer_is_warning_and_suppressible(tmp_path):
    files = {"writer.py": """
        def go(journal):
            journal.record("orphan", "write_only", a=1)
        """}
    [f] = _unsup(analyze_paths(_write_tree(tmp_path, files),
                               select=["RF014"]))
    assert f.severity == "warning" and "orphan/write_only" in f.message
    files_ok = {"writer.py": """
        def go(journal):
            # lint: disable=RF014 — consumed offline by ops notebooks
            journal.record("orphan", "write_only", a=1)
        """}
    assert _unsup(analyze_paths(_write_tree(tmp_path / "ok", files_ok),
                                select=["RF014"])) == []


def test_rf014_suppression_without_justification_does_not_suppress(
        tmp_path):
    files = {"writer.py": """
        def go(journal):
            journal.record("orphan", "write_only", a=1)  # lint: disable=RF014
        """}
    found = _unsup(analyze_paths(_write_tree(tmp_path, files),
                                 select=["RF014"]))
    assert found and "no justification" in found[0].message


def test_rf014_wholesale_kind_reader_covers_all_names(tmp_path):
    files = {"writer.py": """
        def go(journal):
            journal.record("chaos", "injected", site="x")
        """, "reader.py": """
        def scan(recs):
            return [r for r in recs if r.get("kind") == "chaos"]
        """}
    assert _unsup(analyze_paths(_write_tree(tmp_path, files),
                                select=["RF014"])) == []


# ---------------------------------------------------------------------------
# RF015 — both polarities + the **kwargs degrade
# ---------------------------------------------------------------------------


def test_rf015_fires_on_field_no_writer_emits(tmp_path):
    files = {"writer.py": """
        def go(journal):
            journal.record("mesh", "pack_formed", chip=0)
        """, "reader.py": _FIXTURE_READER}
    [f] = _unsup(analyze_paths(_write_tree(tmp_path, files),
                               select=["RF015"]))
    assert "fill_ratio" in f.message and f.path.endswith("reader.py")
    assert "writer.py" in f.message


def test_rf015_quiet_when_written_and_on_open_field_sets(tmp_path):
    paths = _write_tree(tmp_path, {"writer.py": _FIXTURE_WRITER,
                                   "reader.py": _FIXTURE_READER})
    assert _unsup(analyze_paths(paths, select=["RF015"])) == []
    # **kwargs writer: field set open, checker must stay silent
    files = {"writer.py": """
        def go(journal, extra):
            journal.record("mesh", "pack_formed", **extra)
        """, "reader.py": _FIXTURE_READER}
    assert _unsup(analyze_paths(_write_tree(tmp_path / "open", files),
                                select=["RF015"])) == []


def test_rf015_implicit_fields_never_flagged(tmp_path):
    files = {"writer.py": _FIXTURE_WRITER, "reader.py": """
        def scan(recs):
            for r in recs:
                if r.get("kind") == "mesh" and r.get("name") == "pack_formed":
                    yield r.get("ts"), r.get("trace_id"), r.get("pid")
        """}
    assert _unsup(analyze_paths(_write_tree(tmp_path, files),
                                select=["RF015"])) == []


# ---------------------------------------------------------------------------
# RF016 — divergence and propagation, both polarities
# ---------------------------------------------------------------------------


def test_rf016_fires_on_divergent_defaults_listing_all_sites(tmp_path):
    files = {"liba.py": """
        import os
        x = int(os.environ.get("RAFIKI_WIDTH", "1"))
        """, "libb.py": """
        import os
        x = int(os.environ.get("RAFIKI_WIDTH", "4"))
        """}
    [f] = _unsup(analyze_paths(_write_tree(tmp_path, files),
                               select=["RF016"]))
    assert "RAFIKI_WIDTH" in f.message
    assert "liba.py" in f.message and "libb.py" in f.message


def test_rf016_quiet_on_same_required_or_dynamic_defaults(tmp_path):
    files = {"liba.py": """
        import os
        x = int(os.environ.get("RAFIKI_WIDTH", "4"))
        y = os.environ["RAFIKI_OTHER"]
        """, "libb.py": """
        import os
        x = int(os.environ.get("RAFIKI_WIDTH", "4"))
        y = os.environ.get("RAFIKI_OTHER", f"{1}")
        """}
    assert _unsup(analyze_paths(_write_tree(tmp_path, files),
                                select=["RF016"])) == []


def test_rf016_unpropagated_knob_in_spawned_child(tmp_path):
    files = {"child.py": """
        import os
        WIDTH = int(os.environ.get("RAFIKI_WIDTH", "1"))
        """, "parent.py": """
        import subprocess, sys
        def spawn():
            env = {"PATH": "/bin"}
            subprocess.Popen([sys.executable, "-m", "child"], env=env)
        """}
    [f] = _unsup(analyze_paths(_write_tree(tmp_path, files),
                               select=["RF016"]))
    assert "RAFIKI_WIDTH" in f.message and f.path.endswith("parent.py")


def test_rf016_quiet_when_spawn_inherits_or_propagates(tmp_path):
    files = {"child.py": """
        import os
        WIDTH = int(os.environ.get("RAFIKI_WIDTH", "1"))
        """, "parent.py": """
        import os, subprocess, sys
        def spawn():
            env = dict(os.environ)
            subprocess.Popen([sys.executable, "-m", "child"], env=env)
        def spawn_explicit():
            env = {"PATH": "/bin", "RAFIKI_WIDTH": "4"}
            subprocess.Popen([sys.executable, "-m", "child"], env=env)
        """}
    assert _unsup(analyze_paths(_write_tree(tmp_path, files),
                                select=["RF016"])) == []
