"""Packed single-transfer pytree serialization (utils/serial.py)."""

import numpy as np
import pytest

import jax.numpy as jnp

from rafiki_tpu.utils.serial import dump_pytree, is_packed, load_pytree


def test_round_trip_full_precision():
    tree = {
        "dense": {"kernel": np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32),
                  "bias": np.zeros((4,), np.float32)},
        "step": np.int32(17),
        "scale": np.float32(2.5),
    }
    blob = dump_pytree(tree, cast_f32_to_bf16=False)
    assert is_packed(blob)
    out = load_pytree(blob)
    np.testing.assert_array_equal(out["dense"]["kernel"], tree["dense"]["kernel"])
    np.testing.assert_array_equal(out["dense"]["bias"], tree["dense"]["bias"])
    assert int(out["step"]) == 17
    assert float(out["scale"]) == 2.5


def test_bf16_cast_halves_floats_only():
    import ml_dtypes

    tree = {"w": np.ones((16,), np.float32) * 1.5,
            "idx": np.arange(4, dtype=np.int32)}
    blob = dump_pytree(tree, cast_f32_to_bf16=True)
    out = load_pytree(blob)
    assert out["w"].dtype == np.dtype(ml_dtypes.bfloat16)
    np.testing.assert_array_equal(out["w"].astype(np.float32), tree["w"])
    assert out["idx"].dtype == np.int32
    np.testing.assert_array_equal(out["idx"], tree["idx"])


def test_tuple_state_and_bf16_leaves():
    state = (
        {"k": jnp.ones((3, 3), jnp.bfloat16)},
        jnp.zeros((), jnp.int32),
    )
    out = load_pytree(dump_pytree(state, cast_f32_to_bf16=True))
    # flax state-dict addresses tuple slots as "0", "1"
    assert out["0"]["k"].shape == (3, 3)
    assert int(out["1"]) == 0


def test_empty_tree():
    assert load_pytree(dump_pytree({})) == {}


def test_reject_garbage():
    with pytest.raises(ValueError):
        load_pytree(b"not a packed blob")


def test_model_params_round_trip_serving_math():
    """dump_parameters -> load_parameters must preserve predictions
    exactly (bf16 storage is math-identical for bf16-compute modules)."""
    from rafiki_tpu.models.ff import FeedForward

    tr = "synthetic://images?classes=4&n=128&w=8&h=8&c=1&seed=0"
    m1 = FeedForward(hidden_layers=1, hidden_units=32, learning_rate=1e-3,
                     batch_size=32, epochs=1, seed=0)
    m1.train(tr)
    q = np.random.default_rng(3).uniform(0, 1, size=(8, 8, 8, 1)).astype(np.float32)
    p1 = np.asarray(m1.predict_proba(q))
    blob = m1.dump_parameters()

    m2 = FeedForward(hidden_layers=1, hidden_units=32, learning_rate=1e-3,
                     batch_size=32, epochs=1, seed=0)
    m2.load_parameters(blob)
    p2 = np.asarray(m2.predict_proba(q))
    np.testing.assert_allclose(p1, p2, rtol=1e-2, atol=1e-3)
    assert np.array_equal(p1.argmax(-1), p2.argmax(-1))
