"""In-job elasticity: the scheduler must survive worker death mid-job.

SURVEY.md §5 failure-detection row — the reference got crash-restart
for free from Docker Swarm's restart policy; here the ProcessScheduler
supervise loop is the restart policy: a worker group any member of
which dies is torn down and respawned (bounded retries, backoff), and
the replacement leader CAS-adopts the dead worker's orphaned RUNNING
trial so the job still completes its exact trial budget.

The kill is made deterministic by model templates that SIGKILL their
own worker process from inside train() — first attempt only, gated on
a flag file — which is exactly the mid-trial death window (trial row
exists and is RUNNING, params not yet persisted).
"""

import pathlib
import threading
import time

import pytest

from rafiki_tpu.scheduler import ProcessScheduler
from rafiki_tpu.store import MetaStore, ParamsStore

from tests.test_scheduler import FF_SOURCE, TRAIN, VAL

CRASH_ONCE_SRC = FF_SOURCE.replace(
    b"class TinyFF(JaxModel):",
    b"""class CrashOnceFF(JaxModel):
    def train(self, uri):
        import os, pathlib
        flag = pathlib.Path(os.environ["RAFIKI_TEST_CRASH_FLAG"])
        if not flag.exists():
            flag.write_text("crashed")
            os.kill(os.getpid(), 9)  # SIGKILL: no cleanup, no excepthook
        super().train(uri)
""").replace(b'"TinyFF"', b'"CrashOnceFF"')

ALWAYS_CRASH_SRC = FF_SOURCE.replace(
    b"class TinyFF(JaxModel):",
    b"""class AlwaysCrashFF(JaxModel):
    def train(self, uri):
        import os
        os.kill(os.getpid(), 9)
""").replace(b'"TinyFF"', b'"AlwaysCrashFF"')

# Multihost variants: only the named group process kills itself, and
# only once — the other process blocks in (or heads toward) a
# collective its peer abandoned, which the scheduler must tear down
# directly instead of waiting out the gloo transport timeout.
_MH_CRASH_TMPL = b"""class MhCrashFF(JaxModel):
    def train(self, uri):
        import os, pathlib
        import jax
        flag = pathlib.Path(os.environ["RAFIKI_TEST_CRASH_FLAG"])
        if jax.process_index() == %d and not flag.exists():
            flag.write_text("crashed")
            os.kill(os.getpid(), 9)
        super().train(uri)
"""


def _mh_crash_src(process_index: int) -> bytes:
    return FF_SOURCE.replace(
        b"class TinyFF(JaxModel):", _MH_CRASH_TMPL % process_index,
    ).replace(b'"TinyFF"', b'"MhCrashFF"')


@pytest.fixture()
def env(tmp_path, monkeypatch):
    monkeypatch.setenv("RAFIKI_TEST_CRASH_FLAG", str(tmp_path / "crash.flag"))
    monkeypatch.setenv("RAFIKI_WORKER_RESTART_BACKOFF_S", "0.1")
    store = MetaStore(tmp_path / "meta.sqlite3")
    params = ParamsStore(tmp_path / "params")
    return store, params, tmp_path


def _job(store, model, budget):
    job = store.create_train_job("elasticapp", "IMAGE_CLASSIFICATION", None,
                                 TRAIN, VAL, budget)
    store.create_sub_train_job(job["id"], model["id"])
    return job


def test_sigkilled_worker_restarts_and_budget_completes(env):
    """kill -9 mid-trial: the job must still complete its FULL budget —
    the orphaned trial is adopted (not errored and replaced) and the
    remaining trials run on the replacement worker."""
    store, params, tmp = env
    model = store.create_model("crashff", "IMAGE_CLASSIFICATION", None,
                               CRASH_ONCE_SRC, "CrashOnceFF")
    job = _job(store, model, {"MODEL_TRIAL_COUNT": 3})
    result = ProcessScheduler(store, params).run_train_job(
        job["id"], n_workers=1, advisor_kind="random", platform="cpu",
        poll_s=0.2)
    assert (tmp / "crash.flag").exists(), "the crash never happened"
    assert result.status == "COMPLETED", result.errors
    assert len(result.trials) == 3, "budget shrank or overshot after restart"
    assert all(t["status"] == "COMPLETED" for t in result.trials)
    # Every surviving trial ran on (or was adopted by) the restarted
    # worker, whose id carries the attempt suffix.
    assert {t["worker_id"] for t in result.trials} == \
        {f"{job['id'][:8]}-p0-r1"}
    # The adopted trial's params are loadable like any other's.
    assert len(params.load(result.best_trials[0]["params_id"])) > 100


def test_restarts_exhausted_marks_job_errored(env, monkeypatch):
    """A worker that dies on every attempt must not loop forever: after
    max_restarts the group is given up, its orphan is marked ERRORED,
    and the failure is recorded on the result."""
    store, params, _ = env
    monkeypatch.setenv("RAFIKI_WORKER_MAX_RESTARTS", "1")
    model = store.create_model("alwayscrash", "IMAGE_CLASSIFICATION", None,
                               ALWAYS_CRASH_SRC, "AlwaysCrashFF")
    job = _job(store, model, {"MODEL_TRIAL_COUNT": 2})
    result = ProcessScheduler(store, params).run_train_job(
        job["id"], n_workers=1, advisor_kind="random", platform="cpu",
        poll_s=0.2)
    assert result.status == "ERRORED"
    assert result.errors, "permanent worker death left no trace"
    assert all(t["status"] == "ERRORED" for t in result.trials)
    assert all("restarts exhausted" in (t["error"] or "")
               for t in result.trials)


def test_stop_during_backoff_terminates_orphan(env, monkeypatch):
    """Stopping a job while a crashed group waits out its restart
    backoff must not leave the orphaned trial RUNNING — a later
    periodic recovery sweep would resurrect a trial of a job the user
    explicitly stopped."""
    store, params, _ = env
    monkeypatch.setenv("RAFIKI_WORKER_RESTART_BACKOFF_S", "60")
    model = store.create_model("alwayscrash", "IMAGE_CLASSIFICATION", None,
                               ALWAYS_CRASH_SRC, "AlwaysCrashFF")
    job = _job(store, model, {"MODEL_TRIAL_COUNT": 5})
    stop = threading.Event()
    out = {}

    def run():
        out["result"] = ProcessScheduler(store, params).run_train_job(
            job["id"], n_workers=1, advisor_kind="random", platform="cpu",
            poll_s=0.2, stop_event=stop)

    th = threading.Thread(target=run, daemon=True)
    th.start()
    # Wait until the crash landed the group in its 60s backoff window
    # (trial exists and its worker is dead), then stop the job.
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        trials = store.get_trials_of_train_job(job["id"])
        if trials:
            time.sleep(2)  # let the supervise loop notice the corpse
            break
        time.sleep(0.2)
    stop.set()
    th.join(timeout=60)
    assert not th.is_alive()
    assert out["result"].status == "STOPPED"
    for t in store.get_trials_of_train_job(job["id"]):
        assert t["status"] in ("TERMINATED", "COMPLETED", "ERRORED"), \
            f"orphan left {t['status']} on a stopped job"


@pytest.mark.parametrize("crash_process", [1, 0],
                         ids=["follower-killed", "leader-killed"])
def test_multihost_group_member_sigkill_respawns_group(env, crash_process):
    """kill -9 one member of a 2-process dp group: the scheduler tears
    the whole group down at once (no transport-timeout wait) and
    respawns it; the new leader adopts the orphan and the budget still
    completes."""
    store, params, tmp = env
    model = store.create_model("mhcrash", "IMAGE_CLASSIFICATION", None,
                               _mh_crash_src(crash_process), "MhCrashFF")
    job = _job(store, model, {"MODEL_TRIAL_COUNT": 2})
    t0 = time.monotonic()
    result = ProcessScheduler(store, params).run_train_job(
        job["id"], n_workers=1, devices_per_trial=1, advisor_kind="random",
        platform="cpu", poll_s=0.2, multihost_processes=2)
    wall = time.monotonic() - t0
    assert (tmp / "crash.flag").exists(), "the crash never happened"
    assert result.status == "COMPLETED", result.errors
    completed = [t for t in result.trials if t["status"] == "COMPLETED"]
    assert len(completed) == 2
    # Group teardown is direct process supervision; it must not have
    # waited out a multi-minute collective transport timeout.
    assert wall < 180, f"group teardown took {wall:.0f}s — timeout-bound?"


# ---------------------------------------------------------------------------
# Mesh sweep elasticity (docs/mesh_sweep.md): k packed trials per chip
# × N chips, re-packed onto survivors when a chip is lost, degraded to
# single-chip mode when the mesh cannot form. CPU mesh: the conftest
# pins 8 virtual host devices.
# ---------------------------------------------------------------------------

# ChaosFF (3 epochs, lr the only tuned knob → ONE packing bucket, so
# assignment splits deterministically across chips) and EvictFF (its
# early-stop variant) come from the chaos catalog — same fixtures the
# scenario runner exercises.
from rafiki_tpu.chaos.scenarios import EVICT_SOURCE  # noqa: E402
from rafiki_tpu.chaos.scenarios import FF_SOURCE as CHAOS_FF_SOURCE  # noqa: E402


def test_mesh_sweep_packs_trials_across_chips(env):
    from rafiki_tpu.scheduler import MeshSweepScheduler

    store, params, _ = env
    model = store.create_model("chaosff", "IMAGE_CLASSIFICATION", None,
                               CHAOS_FF_SOURCE, "ChaosFF")
    job = _job(store, model, {"MODEL_TRIAL_COUNT": 4})
    result = MeshSweepScheduler(store, params).run_sweep(
        job["id"], chips=2, trials_per_chip=2, advisor_kind="random")
    assert result.status == "COMPLETED", result.errors
    assert len(result.trials) == 4
    assert all(t["status"] == "COMPLETED" for t in result.trials)
    assert all(t.get("score") is not None for t in result.trials)
    # One packing bucket round-robins across both chips: each trained 2.
    workers = sorted({t["worker_id"] for t in result.trials})
    assert workers == [f"{job['id'][:8]}-mesh-c0", f"{job['id'][:8]}-mesh-c1"]


def test_mesh_chip_killed_mid_sweep_repacks_onto_survivor(env, monkeypatch):
    from rafiki_tpu import telemetry
    from rafiki_tpu.chaos import FaultPlane, install, uninstall
    from rafiki_tpu.scheduler import MeshSweepScheduler

    store, params, _ = env
    monkeypatch.setenv("RAFIKI_CHECKPOINT_EVERY", "1")
    model = store.create_model("chaosff", "IMAGE_CLASSIFICATION", None,
                               CHAOS_FF_SOURCE, "ChaosFF")
    job = _job(store, model, {"MODEL_TRIAL_COUNT": 4})
    telemetry.reset()
    install(FaultPlane.from_spec(
        "seed=11;scheduler.preempt:kill:after=2:times=1:match=chip1"))
    try:
        result = MeshSweepScheduler(store, params).run_sweep(
            job["id"], chips=2, trials_per_chip=2, advisor_kind="random")
    finally:
        uninstall()
    assert result.status == "COMPLETED", result.errors
    assert len(result.trials) == 4, "chip loss lost or duplicated rows"
    assert all(t["status"] == "COMPLETED" for t in result.trials)
    assert all(t.get("score") is not None for t in result.trials), \
        "a surviving trial finished without a recorded score"
    assert telemetry.get_counter("mesh.chips_lost") >= 1.0
    # The re-packed rows finished under the surviving chip's worker.
    assert any((t["worker_id"] or "").endswith("-mesh-c0")
               for t in result.trials)


def test_pack_straggler_evicted_and_backfilled(env):
    from rafiki_tpu import telemetry
    from rafiki_tpu.advisor import AdvisorService
    from rafiki_tpu.model.base import load_model_class
    from rafiki_tpu.model.knobs import knob_config_signature
    from rafiki_tpu.worker.train import (InProcAdvisorHandle,
                                         PackedTrialRunner, TrainWorker)

    store, params, _ = env
    telemetry.reset()
    model = store.create_model("evictff", "IMAGE_CLASSIFICATION", None,
                               EVICT_SOURCE, "EvictFF")
    job = _job(store, model, {"MODEL_TRIAL_COUNT": 3})
    sub = store.get_sub_train_jobs(job["id"])[0]
    cls = load_model_class(EVICT_SOURCE, "EvictFF")
    advisors = AdvisorService()
    advisor_id = advisors.create_advisor(cls.get_knob_config(), kind="random")
    worker = TrainWorker(
        store, params, sub["id"], cls,
        InProcAdvisorHandle(advisors, advisor_id), TRAIN, VAL,
        {"MODEL_TRIAL_COUNT": 3}, worker_id="evict-w0", async_persist=False)
    knob_config = cls.get_knob_config()
    base = {"hidden_units": 16, "batch_size": 32, "epochs": 3}
    rows = []
    # lr >= 0.02 trips EvictFF.should_stop_early at epoch 0: member 0
    # is the straggler, member 1 trains its full 3-epoch budget.
    for kn in (dict(base, learning_rate=0.025),
               dict(base, learning_rate=0.005)):
        trial = store.create_trial(
            sub["id"], "EvictFF", kn,
            shape_sig=knob_config_signature(knob_config, kn), budget_max=3)
        rows.append((trial["id"], kn))
    n = PackedTrialRunner(worker, 2).run_assigned(rows, budget_max=3)
    assert n == 3, "the freed slot was not backfilled"
    trials = store.get_trials_of_train_job(job["id"])
    assert len(trials) == 3
    assert all(t["status"] == "COMPLETED" for t in trials)
    assert all(t.get("score") is not None for t in trials)
    assert telemetry.get_counter("trial_pack.evictions") >= 1.0, \
        "the straggler was never evicted from the stacked state"
    assert telemetry.get_counter("trial_pack.backfills") >= 1.0, \
        "no freshly proposed trial was admitted mid-pack"


def test_mesh_backfill_respects_trial_budget(env):
    """Mid-pack backfill on the MESH path must claim atomic budget
    slots. Threshold 0.005 puts the seed-0 proposal sequence (0.0087,
    0.0025, 0.0011, …) one early-stopper per pack round: each eviction
    frees a slot that backfill refills until MODEL_TRIAL_COUNT drains.
    Without budget_max threaded through the chip runner into
    run_assigned, backfill's create_trial skips the slot claim — trials
    exceed the budget and the pack never drains (this test hanging is
    the failure mode)."""
    from rafiki_tpu.scheduler import MeshSweepScheduler

    store, params, _ = env
    src = EVICT_SOURCE.replace(b">= 0.02", b">= 0.005")
    model = store.create_model("allstopff", "IMAGE_CLASSIFICATION", None,
                               src, "EvictFF")
    job = _job(store, model, {"MODEL_TRIAL_COUNT": 5})
    result = MeshSweepScheduler(store, params).run_sweep(
        job["id"], chips=1, trials_per_chip=2, advisor_kind="random")
    assert result.status == "COMPLETED", result.errors
    trials = store.get_trials_of_train_job(job["id"])
    assert len(trials) == 5, \
        f"backfill bypassed the trial-count budget ({len(trials)} rows)"
    assert all(t["status"] == "COMPLETED" for t in trials)
    assert all(t.get("score") is not None for t in trials)


def test_mesh_degrades_to_single_chip(env, monkeypatch):
    from rafiki_tpu import telemetry
    from rafiki_tpu.chaos import FaultPlane, install, uninstall
    from rafiki_tpu.scheduler import MeshSweepScheduler

    store, params, _ = env
    monkeypatch.setenv("RAFIKI_MESH_INIT_RETRIES", "2")
    monkeypatch.setenv("RAFIKI_MESH_INIT_BACKOFF_S", "0.01")
    monkeypatch.setenv("RAFIKI_MESH_FORM_GRACE_S", "5")
    model = store.create_model("chaosff", "IMAGE_CLASSIFICATION", None,
                               CHAOS_FF_SOURCE, "ChaosFF")
    job = _job(store, model, {"MODEL_TRIAL_COUNT": 2})
    telemetry.reset()
    install(FaultPlane.from_spec("seed=17;collective.init:error:times=8"))
    try:
        result = MeshSweepScheduler(store, params).run_sweep(
            job["id"], chips=2, trials_per_chip=2, advisor_kind="random")
    finally:
        uninstall()
    assert result.status == "COMPLETED", result.errors
    assert len(result.trials) == 2
    assert all(t["status"] == "COMPLETED" for t in result.trials)
    assert telemetry.get_counter("mesh.degraded_single_chip") >= 1.0
    assert telemetry.get_counter("mesh.init_retries") >= 2.0
    # Everything ran on the single surviving chip's worker.
    assert {t["worker_id"] for t in result.trials} == \
        {f"{job['id'][:8]}-mesh-c0"}
