"""In-job elasticity: the scheduler must survive worker death mid-job.

SURVEY.md §5 failure-detection row — the reference got crash-restart
for free from Docker Swarm's restart policy; here the ProcessScheduler
supervise loop is the restart policy: a worker group any member of
which dies is torn down and respawned (bounded retries, backoff), and
the replacement leader CAS-adopts the dead worker's orphaned RUNNING
trial so the job still completes its exact trial budget.

The kill is made deterministic by model templates that SIGKILL their
own worker process from inside train() — first attempt only, gated on
a flag file — which is exactly the mid-trial death window (trial row
exists and is RUNNING, params not yet persisted).
"""

import pathlib
import threading
import time

import pytest

from rafiki_tpu.scheduler import ProcessScheduler
from rafiki_tpu.store import MetaStore, ParamsStore

from tests.test_scheduler import FF_SOURCE, TRAIN, VAL

CRASH_ONCE_SRC = FF_SOURCE.replace(
    b"class TinyFF(JaxModel):",
    b"""class CrashOnceFF(JaxModel):
    def train(self, uri):
        import os, pathlib
        flag = pathlib.Path(os.environ["RAFIKI_TEST_CRASH_FLAG"])
        if not flag.exists():
            flag.write_text("crashed")
            os.kill(os.getpid(), 9)  # SIGKILL: no cleanup, no excepthook
        super().train(uri)
""").replace(b'"TinyFF"', b'"CrashOnceFF"')

ALWAYS_CRASH_SRC = FF_SOURCE.replace(
    b"class TinyFF(JaxModel):",
    b"""class AlwaysCrashFF(JaxModel):
    def train(self, uri):
        import os
        os.kill(os.getpid(), 9)
""").replace(b'"TinyFF"', b'"AlwaysCrashFF"')

# Multihost variants: only the named group process kills itself, and
# only once — the other process blocks in (or heads toward) a
# collective its peer abandoned, which the scheduler must tear down
# directly instead of waiting out the gloo transport timeout.
_MH_CRASH_TMPL = b"""class MhCrashFF(JaxModel):
    def train(self, uri):
        import os, pathlib
        import jax
        flag = pathlib.Path(os.environ["RAFIKI_TEST_CRASH_FLAG"])
        if jax.process_index() == %d and not flag.exists():
            flag.write_text("crashed")
            os.kill(os.getpid(), 9)
        super().train(uri)
"""


def _mh_crash_src(process_index: int) -> bytes:
    return FF_SOURCE.replace(
        b"class TinyFF(JaxModel):", _MH_CRASH_TMPL % process_index,
    ).replace(b'"TinyFF"', b'"MhCrashFF"')


@pytest.fixture()
def env(tmp_path, monkeypatch):
    monkeypatch.setenv("RAFIKI_TEST_CRASH_FLAG", str(tmp_path / "crash.flag"))
    monkeypatch.setenv("RAFIKI_WORKER_RESTART_BACKOFF_S", "0.1")
    store = MetaStore(tmp_path / "meta.sqlite3")
    params = ParamsStore(tmp_path / "params")
    return store, params, tmp_path


def _job(store, model, budget):
    job = store.create_train_job("elasticapp", "IMAGE_CLASSIFICATION", None,
                                 TRAIN, VAL, budget)
    store.create_sub_train_job(job["id"], model["id"])
    return job


def test_sigkilled_worker_restarts_and_budget_completes(env):
    """kill -9 mid-trial: the job must still complete its FULL budget —
    the orphaned trial is adopted (not errored and replaced) and the
    remaining trials run on the replacement worker."""
    store, params, tmp = env
    model = store.create_model("crashff", "IMAGE_CLASSIFICATION", None,
                               CRASH_ONCE_SRC, "CrashOnceFF")
    job = _job(store, model, {"MODEL_TRIAL_COUNT": 3})
    result = ProcessScheduler(store, params).run_train_job(
        job["id"], n_workers=1, advisor_kind="random", platform="cpu",
        poll_s=0.2)
    assert (tmp / "crash.flag").exists(), "the crash never happened"
    assert result.status == "COMPLETED", result.errors
    assert len(result.trials) == 3, "budget shrank or overshot after restart"
    assert all(t["status"] == "COMPLETED" for t in result.trials)
    # Every surviving trial ran on (or was adopted by) the restarted
    # worker, whose id carries the attempt suffix.
    assert {t["worker_id"] for t in result.trials} == \
        {f"{job['id'][:8]}-p0-r1"}
    # The adopted trial's params are loadable like any other's.
    assert len(params.load(result.best_trials[0]["params_id"])) > 100


def test_restarts_exhausted_marks_job_errored(env, monkeypatch):
    """A worker that dies on every attempt must not loop forever: after
    max_restarts the group is given up, its orphan is marked ERRORED,
    and the failure is recorded on the result."""
    store, params, _ = env
    monkeypatch.setenv("RAFIKI_WORKER_MAX_RESTARTS", "1")
    model = store.create_model("alwayscrash", "IMAGE_CLASSIFICATION", None,
                               ALWAYS_CRASH_SRC, "AlwaysCrashFF")
    job = _job(store, model, {"MODEL_TRIAL_COUNT": 2})
    result = ProcessScheduler(store, params).run_train_job(
        job["id"], n_workers=1, advisor_kind="random", platform="cpu",
        poll_s=0.2)
    assert result.status == "ERRORED"
    assert result.errors, "permanent worker death left no trace"
    assert all(t["status"] == "ERRORED" for t in result.trials)
    assert all("restarts exhausted" in (t["error"] or "")
               for t in result.trials)


def test_stop_during_backoff_terminates_orphan(env, monkeypatch):
    """Stopping a job while a crashed group waits out its restart
    backoff must not leave the orphaned trial RUNNING — a later
    periodic recovery sweep would resurrect a trial of a job the user
    explicitly stopped."""
    store, params, _ = env
    monkeypatch.setenv("RAFIKI_WORKER_RESTART_BACKOFF_S", "60")
    model = store.create_model("alwayscrash", "IMAGE_CLASSIFICATION", None,
                               ALWAYS_CRASH_SRC, "AlwaysCrashFF")
    job = _job(store, model, {"MODEL_TRIAL_COUNT": 5})
    stop = threading.Event()
    out = {}

    def run():
        out["result"] = ProcessScheduler(store, params).run_train_job(
            job["id"], n_workers=1, advisor_kind="random", platform="cpu",
            poll_s=0.2, stop_event=stop)

    th = threading.Thread(target=run, daemon=True)
    th.start()
    # Wait until the crash landed the group in its 60s backoff window
    # (trial exists and its worker is dead), then stop the job.
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        trials = store.get_trials_of_train_job(job["id"])
        if trials:
            time.sleep(2)  # let the supervise loop notice the corpse
            break
        time.sleep(0.2)
    stop.set()
    th.join(timeout=60)
    assert not th.is_alive()
    assert out["result"].status == "STOPPED"
    for t in store.get_trials_of_train_job(job["id"]):
        assert t["status"] in ("TERMINATED", "COMPLETED", "ERRORED"), \
            f"orphan left {t['status']} on a stopped job"


@pytest.mark.parametrize("crash_process", [1, 0],
                         ids=["follower-killed", "leader-killed"])
def test_multihost_group_member_sigkill_respawns_group(env, crash_process):
    """kill -9 one member of a 2-process dp group: the scheduler tears
    the whole group down at once (no transport-timeout wait) and
    respawns it; the new leader adopts the orphan and the budget still
    completes."""
    store, params, tmp = env
    model = store.create_model("mhcrash", "IMAGE_CLASSIFICATION", None,
                               _mh_crash_src(crash_process), "MhCrashFF")
    job = _job(store, model, {"MODEL_TRIAL_COUNT": 2})
    t0 = time.monotonic()
    result = ProcessScheduler(store, params).run_train_job(
        job["id"], n_workers=1, devices_per_trial=1, advisor_kind="random",
        platform="cpu", poll_s=0.2, multihost_processes=2)
    wall = time.monotonic() - t0
    assert (tmp / "crash.flag").exists(), "the crash never happened"
    assert result.status == "COMPLETED", result.errors
    completed = [t for t in result.trials if t["status"] == "COMPLETED"]
    assert len(completed) == 2
    # Group teardown is direct process supervision; it must not have
    # waited out a multi-minute collective transport timeout.
    assert wall < 180, f"group teardown took {wall:.0f}s — timeout-bound?"
