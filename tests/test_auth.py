"""Auth unit tests: password hashing, JWT round-trips, role checks."""

import time

import pytest

from rafiki_tpu.utils.auth import (
    AuthError,
    check_user_type,
    decode_token,
    generate_token,
    hash_password,
    verify_password,
)


def test_password_hash_roundtrip():
    stored = hash_password("hunter2")
    assert verify_password("hunter2", stored)
    assert not verify_password("hunter3", stored)
    assert stored != hash_password("hunter2")  # fresh salt every time


def test_password_bad_format():
    assert not verify_password("x", "not-a-hash")
    assert not verify_password("x", "")


def test_jwt_roundtrip():
    token = generate_token({"user_id": "u1", "user_type": "ADMIN"}, "secret")
    payload = decode_token(token, "secret")
    assert payload["user_id"] == "u1"
    assert payload["user_type"] == "ADMIN"


def test_jwt_bad_signature():
    token = generate_token({"user_id": "u1"}, "secret")
    with pytest.raises(AuthError):
        decode_token(token, "other-secret")
    with pytest.raises(AuthError):
        decode_token(token[:-4] + "AAAA", "secret")


def test_jwt_expiry():
    token = generate_token({"user_id": "u1"}, "s", ttl_s=-1)
    with pytest.raises(AuthError, match="expired"):
        decode_token(token, "s")
    token = generate_token({"user_id": "u1"}, "s", ttl_s=60)
    assert decode_token(token, "s")["user_id"] == "u1"


def test_jwt_malformed():
    for bad in ("", "abc", "a.b", "a.b.c"):
        with pytest.raises(AuthError):
            decode_token(bad, "s")


def test_role_ladder():
    check_user_type("MODEL_DEVELOPER", ["MODEL_DEVELOPER"])
    check_user_type("ADMIN", ["MODEL_DEVELOPER"])       # admins can do anything
    check_user_type("SUPERADMIN", ["APP_DEVELOPER"])
    with pytest.raises(AuthError):
        check_user_type("APP_DEVELOPER", ["MODEL_DEVELOPER"])
    with pytest.raises(AuthError):
        check_user_type("", ["ADMIN"])
