"""Compile amortization: same-signature trials must NOT recompile.

This is the throughput decider (SURVEY.md §7 hard part #2): a worker
runs trials back to back, and every retrace/recompile it pays between
trials comes straight out of trials/hour. The contract under test:

  * two trials whose traced computation is identical — same model
    class, same shape-affecting knobs, ANY lr / warmup / dropout /
    epochs / seed — share one cached ``Program`` AND one compiled XLA
    executable (``jit._cache_size() == 1``);
  * the dynamic-hyperparameter path is numerically equivalent to the
    baked-optimizer path it replaces;
  * trials that do change the architecture get their own program.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from rafiki_tpu.models.ff import FeedForward
from rafiki_tpu.models.vgg import Vgg
from rafiki_tpu.ops.train import (
    TrainLoop,
    cross_entropy_loss,
    dropout,
    program_cache_stats,
)

TRAIN = "synthetic://images?classes=4&n=128&w=8&h=8&c=1&seed=0"
VAL = "synthetic://images?classes=4&n=64&w=8&h=8&c=1&seed=1"


def _ff_knobs(**over):
    knobs = dict(hidden_layers=1, hidden_units=32, learning_rate=1e-3,
                 batch_size=32, epochs=1, seed=0)
    knobs.update(over)
    return knobs


def _run_trial(model_cls, knobs):
    model = model_cls(**knobs)
    model.train(TRAIN)
    model.evaluate(VAL)
    return model


def test_second_same_sig_trial_reuses_program():
    """The core amortization claim: trial 2 (different lr, epochs,
    seed) is a pure cache hit — same Program object, no new compiled
    executable in the jit cache."""
    m1 = _run_trial(FeedForward, _ff_knobs())
    prog1 = m1._loop.program
    before = program_cache_stats()
    # Trials run epochs through the device-resident scan program.
    n_exec_before = prog1.train_epoch._cache_size()

    m2 = _run_trial(FeedForward, _ff_knobs(learning_rate=3e-2, epochs=2))
    after = program_cache_stats()

    assert m2._loop.program is prog1
    assert after["misses"] == before["misses"], "second trial compiled a new program"
    assert after["hits"] == before["hits"] + 1
    # the jitted epoch served trial 2 from its existing executable
    assert prog1.train_epoch._cache_size() == n_exec_before
    m1.destroy(), m2.destroy()


def test_vgg_dropout_and_lr_are_dynamic():
    """VGG's continuous knobs (dropout, lr) ride in the traced hyper
    dict: sweeping them reuses ONE program (this is what makes a GP
    sweep over the VGG space compile ~once per shape bucket)."""
    base = dict(depth=11, width_mult=0.25, dropout=0.1, learning_rate=1e-3,
                batch_size=64, epochs=1, seed=0)
    tr = "synthetic://images?classes=4&n=128&w=8&h=8&c=3&seed=0"
    va = "synthetic://images?classes=4&n=64&w=8&h=8&c=3&seed=1"

    m1 = Vgg(**base)
    m1.train(tr)
    m1.evaluate(va)
    prog1 = m1._loop.program
    before = program_cache_stats()

    m2 = Vgg(**dict(base, dropout=0.45, learning_rate=2e-2))
    m2.train(tr)
    m2.evaluate(va)

    assert m2._loop.program is prog1
    assert program_cache_stats()["misses"] == before["misses"]
    assert prog1.train_epoch._cache_size() == 1
    m1.destroy(), m2.destroy()


def test_shape_knob_change_builds_new_program():
    m1 = _run_trial(FeedForward, _ff_knobs())
    before = program_cache_stats()
    m2 = _run_trial(FeedForward, _ff_knobs(hidden_units=64))
    after = program_cache_stats()
    assert m2._loop.program is not m1._loop.program
    assert after["misses"] == before["misses"] + 1
    m1.destroy(), m2.destroy()


def test_worker_trials_hit_program_cache(tmp_path):
    """End-to-end through the TrainWorker loop: a 4-trial job on one
    worker compiles at most once per shape signature."""
    from rafiki_tpu.advisor import AdvisorService
    from rafiki_tpu.store import MetaStore, ParamsStore
    from rafiki_tpu.worker.train import InProcAdvisorHandle, TrainWorker

    store = MetaStore(tmp_path / "meta.sqlite3")
    params = ParamsStore(tmp_path / "params")
    src = open("rafiki_tpu/models/ff.py", "rb").read()
    model = store.create_model("ff", "IMAGE_CLASSIFICATION", None, src, "FeedForward")
    job = store.create_train_job("app", "IMAGE_CLASSIFICATION", None, TRAIN, VAL,
                                 {"MODEL_TRIAL_COUNT": 4})
    sub = store.create_sub_train_job(job["id"], model["id"])

    # Advisor fixed to one shape bucket: only lr/epochs vary.
    class OneSigAdvisor:
        def __init__(self):
            self._i = 0

        def propose(self):
            self._i += 1
            return _ff_knobs(learning_rate=10.0 ** -(1 + self._i % 3))

        def feedback(self, score, knobs):
            pass

    from rafiki_tpu.model.base import load_model_class

    cls = load_model_class(src, "FeedForward")
    worker = TrainWorker(store, params, sub["id"], cls, OneSigAdvisor(),
                         TRAIN, VAL, {"MODEL_TRIAL_COUNT": 4},
                         async_persist=False)
    before = program_cache_stats()
    n = worker.run()
    after = program_cache_stats()
    assert n == 4
    # ≤1 new program for 4 trials; ≥3 cache hits
    assert after["misses"] - before["misses"] <= 1
    assert after["hits"] - before["hits"] >= 3


def test_dynamic_lr_matches_baked_adam():
    """scale_by_adam + traced lr scaling ≡ optax.adam(lr): same init,
    same batches → same params."""
    rng = np.random.default_rng(0)
    batch = {
        "x": rng.uniform(-1, 1, size=(16, 8)).astype(np.float32),
        "y": rng.integers(0, 3, size=(16,)).astype(np.int32),
    }

    def init_fn(key):
        k1, k2 = jax.random.split(key)
        return {"w": jax.random.normal(k1, (8, 3)) * 0.1,
                "b": jnp.zeros((3,))}

    def apply_fn(params, b):
        return b["x"] @ params["w"] + params["b"]

    def loss_fn(params, b, rng):
        loss, acc = cross_entropy_loss(apply_fn(params, b), b["y"])
        return loss, {"acc": acc}

    lr = 3e-3
    dyn = TrainLoop(init_fn, apply_fn, loss_fn, seed=0,
                    hyper={"lr": lr, "warmup": 1.0})
    baked = TrainLoop(init_fn, apply_fn, loss_fn, optax.adam(lr), seed=0)
    dev = dyn.plan.put_batch(batch)
    for _ in range(5):
        dyn.state, _ = dyn._train_step(dyn.state, dev)
        baked.state, _ = baked._train_step(baked.state, dev)
    np.testing.assert_allclose(np.asarray(dyn.params["w"]),
                               np.asarray(baked.params["w"]), rtol=1e-5, atol=1e-6)


def test_traced_dropout_semantics():
    x = jnp.ones((1000,), jnp.float32)
    key = jax.random.PRNGKey(0)
    assert np.allclose(dropout(x, 0.0, key, deterministic=False), x)
    assert np.allclose(dropout(x, 0.7, key, deterministic=True), x)
    out = np.asarray(dropout(x, jnp.float32(0.5), key, deterministic=False))
    kept = out > 0
    assert 0.3 < kept.mean() < 0.7          # ~half survive
    assert np.allclose(out[kept], 2.0)       # inverted scaling
    # traced rate: same compiled fn serves different rates
    f = jax.jit(lambda r: dropout(x, r, key, deterministic=False))
    a, b = f(jnp.float32(0.2)), f(jnp.float32(0.8))
    assert f._cache_size() == 1
    assert (np.asarray(a) > 0).mean() > (np.asarray(b) > 0).mean()
