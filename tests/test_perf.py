"""Perf sentinel (rafiki_tpu/obs/perf/, docs/perf.md): the EWMA+MAD
anomaly detector, the multi-window SLO burn-rate engine (driven on a
fake clock — no sleeps), the breach -> journal -> flight-record chain,
and the scripts/bench_report.py regression gate.

The full live chain (train loop -> profiler -> anomaly -> SLO breach
under injected chaos) is exercised end to end by scripts/perf_smoke.py;
these tests pin the pieces it composes."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from rafiki_tpu import telemetry
from rafiki_tpu.obs import journal as journal_mod
from rafiki_tpu.obs.journal import journal
from rafiki_tpu.obs.perf.anomaly import EwmaMad
from rafiki_tpu.obs.perf.slo import SloEngine, SloSpec, _specs_from_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH_REPORT = os.path.join(REPO, "scripts", "bench_report.py")


@pytest.fixture
def journaled(tmp_path):
    journal.configure(tmp_path, role="test")
    try:
        yield tmp_path
    finally:
        journal.close()


@pytest.fixture
def counters():
    telemetry.reset()
    try:
        yield
    finally:
        telemetry.reset()


# -- EwmaMad -----------------------------------------------------------------


def test_ewma_quiet_on_steady_series():
    d = EwmaMad(warmup=4)
    # +-5% deterministic jitter around 1.0 stays inside the 10% MAD
    # floor band at any k >= 1.
    for i in range(64):
        assert d.observe(1.0 + 0.05 * (-1) ** i) is None


def test_ewma_flags_spike_and_reports_ratio():
    d = EwmaMad(warmup=4, k=4.0)
    for _ in range(10):
        assert d.observe(1.0) is None
    report = d.observe(3.0)
    assert report is not None
    assert report["ratio"] == pytest.approx(3.0)
    assert report["value"] == 3.0
    assert report["threshold"] < 3.0
    assert report["mean"] == pytest.approx(1.0)


def test_ewma_never_flags_during_warmup():
    d = EwmaMad(warmup=8)
    assert d.observe(1.0) is None
    for _ in range(6):  # n stays below warmup for these
        assert d.observe(50.0) is None


def test_ewma_absorbs_anomalies_slowly():
    """A flagged spike moves the mean at a quarter learning rate: one
    outlier must not drag the baseline up to itself."""
    d = EwmaMad(warmup=4, alpha=0.25)
    for _ in range(10):
        d.observe(1.0)
    assert d.observe(10.0) is not None
    assert d.mean < 2.0


def test_ewma_sustained_shift_rebaselines_eventually():
    d = EwmaMad(warmup=4, alpha=0.25)
    for _ in range(10):
        d.observe(1.0)
    flagged = sum(d.observe(3.0) is not None for _ in range(200))
    assert 0 < flagged < 200  # alerts on the shift, then adopts it
    assert d.observe(3.0) is None


def test_ewma_env_knobs(monkeypatch):
    monkeypatch.setenv("RAFIKI_PERF_K", "9.5")
    monkeypatch.setenv("RAFIKI_PERF_WARMUP", "3")
    d = EwmaMad()
    assert d.k == 9.5 and d.warmup == 3
    monkeypatch.setenv("RAFIKI_PERF_K", "not-a-number")
    assert EwmaMad().k == 4.0  # malformed env falls back to default


# -- SloEngine ---------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


def _engine(spec, clock):
    return SloEngine(specs=[spec], tick_s=0.0, clock=clock)


def test_slo_fresh_process_never_alarms(counters):
    clk = _Clock()
    eng = _engine(SloSpec("r", "counter:perf_test.x", 0.0,
                          windows=(10.0,)), clk)
    telemetry.inc("perf_test.x", 100)  # huge, but no window of history
    for t in (0.0, 1.0, 5.0):
        clk.now = t
        assert eng.tick()["r"]["breaching"] == 0


def test_slo_rate_breach_after_window_covered(counters, journaled):
    clk = _Clock()
    eng = _engine(SloSpec("r", "counter:perf_test.x", 0.0,
                          windows=(10.0,)), clk)
    eng.tick()
    telemetry.inc("perf_test.x", 5)
    clk.now = 11.0
    st = eng.tick()["r"]
    assert st["breaching"] == 1
    assert st["value"] == pytest.approx(5.0 / 11.0)


def test_slo_breach_requires_every_window(counters):
    """Multi-window burn rule: the long window must also be covered
    AND burning before the spec alarms."""
    clk = _Clock()
    eng = _engine(SloSpec("r", "counter:perf_test.x", 0.0,
                          windows=(10.0, 100.0)), clk)
    eng.tick()
    telemetry.inc("perf_test.x", 5)
    clk.now = 11.0
    assert eng.tick()["r"]["breaching"] == 0  # 100s window not evaluable
    telemetry.inc("perf_test.x", 5)
    clk.now = 101.0
    assert eng.tick()["r"]["breaching"] == 1  # both windows burning


def test_slo_rate_recovers_when_counter_goes_flat(counters, journaled):
    clk = _Clock()
    eng = _engine(SloSpec("r", "counter:perf_test.x", 0.0,
                          windows=(10.0,)), clk)
    eng.tick()
    telemetry.inc("perf_test.x", 5)
    clk.now = 11.0
    assert eng.tick()["r"]["breaching"] == 1
    for t in (20.0, 30.0, 45.0):  # counter flat -> short-window rate 0
        clk.now = t
        st = eng.tick()["r"]
    assert st["breaching"] == 0
    assert telemetry.snapshot()["counters"].get("slo.recoveries") == 1
    kinds = [(r["kind"], r["name"]) for r in journal_mod.read_dir(journal.log_dir)]
    assert ("slo", "recover") in kinds


def test_slo_level_mode_requires_sustained_violation(counters):
    clk = _Clock()
    eng = _engine(SloSpec("g", "gauge:perf_test.depth", 2.0,
                          windows=(10.0,)), clk)
    telemetry.set_gauge("perf_test.depth", 5.0)
    for t in (0.0, 4.0, 8.0):
        clk.now = t
        assert eng.tick()["g"]["breaching"] == 0  # window not covered
    clk.now = 12.0
    assert eng.tick()["g"]["breaching"] == 1  # > 2.0 for a full window
    telemetry.set_gauge("perf_test.depth", 1.0)  # one in-window dip
    clk.now = 14.0
    assert eng.tick()["g"]["breaching"] == 0


def test_slo_ratio_mode(counters):
    clk = _Clock()
    eng = _engine(SloSpec("s", "ratio:perf_test.shed/"
                               "perf_test.shed+perf_test.ok", 0.05,
                          windows=(10.0,)), clk)
    telemetry.inc("perf_test.ok", 1)
    eng.tick()
    telemetry.inc("perf_test.shed", 2)
    telemetry.inc("perf_test.ok", 8)
    clk.now = 11.0
    st = eng.tick()["s"]
    assert st["breaching"] == 1
    assert st["value"] == pytest.approx(0.2)


def test_slo_min_wall_s_gates_young_engines(counters):
    clk = _Clock()
    eng = _engine(SloSpec("r", "counter:perf_test.x", 0.0,
                          windows=(10.0,), min_wall_s=100.0), clk)
    eng.tick()
    telemetry.inc("perf_test.x", 5)
    clk.now = 50.0
    assert eng.tick()["r"]["breaching"] == 0  # burning, but too young
    telemetry.inc("perf_test.x", 5)
    clk.now = 120.0
    assert eng.tick()["r"]["breaching"] == 1


def test_slo_breach_journals_counts_and_dumps_flight(counters, journaled):
    clk = _Clock()
    eng = _engine(SloSpec("perf_test_burn", "counter:perf_test.x", 0.0,
                          windows=(10.0,)), clk)
    eng.tick()
    telemetry.inc("perf_test.x", 3)
    clk.now = 11.0
    assert eng.tick()["perf_test_burn"]["breaching"] == 1

    assert telemetry.snapshot()["counters"].get("slo.breaches") == 1
    records = journal_mod.read_dir(journal.log_dir)
    breaches = [r for r in records
                if r["kind"] == "slo" and r["name"] == "breach"]
    assert len(breaches) == 1
    assert breaches[0]["slo"] == "perf_test_burn"
    assert breaches[0]["source"] == "counter:perf_test.x"
    flights = list(Path(journaled).glob("flight-*.json"))
    assert len(flights) == 1
    bundle = json.loads(flights[0].read_text())
    assert bundle["reason"] == "slo:perf_test_burn"
    # Re-breach without recovery must not re-fire (edge-triggered).
    telemetry.inc("perf_test.x", 3)
    clk.now = 12.0
    eng.tick()
    assert telemetry.snapshot()["counters"].get("slo.breaches") == 1


def test_slo_maybe_tick_honors_interval(counters):
    clk = _Clock()
    eng = SloEngine(specs=[SloSpec("r", "counter:perf_test.x", 0.0)],
                    tick_s=5.0, clock=clk)
    clk.now = 1.0
    assert eng.maybe_tick() is None  # < tick_s since construction tick
    clk.now = 6.0
    assert eng.maybe_tick() is not None


def test_slo_spec_mode_derivation():
    assert SloSpec("a", "counter:x", 1.0).mode == "rate"
    assert SloSpec("b", "ratio:x/y", 1.0).mode == "ratio"
    assert SloSpec("c", "gauge:x", 1.0).mode == "level"
    assert SloSpec("d", "hist_p99:x", 1.0).mode == "level"
    assert SloSpec("e", "ledger:goodput", 1.0).mode == "level"
    assert SloSpec("f", "ledger:downtime_s", 1.0).mode == "rate"
    with pytest.raises(ValueError):
        SloSpec("g", "counter:x", 1.0, op=">=")
    with pytest.raises(ValueError):
        SloSpec("h", "counter:x", 1.0, windows=())


def test_slo_specs_from_env(monkeypatch, journaled):
    monkeypatch.delenv("RAFIKI_SLO", raising=False)
    assert _specs_from_env() is None  # unset -> engine uses defaults
    monkeypatch.setenv("RAFIKI_SLO", "off")
    assert _specs_from_env() == []
    monkeypatch.setenv("RAFIKI_SLO", json.dumps(
        [{"name": "x", "source": "counter:a.b", "threshold": 1.5,
          "windows": [5, 30]}]))
    specs = _specs_from_env()
    assert [s.name for s in specs] == ["x"]
    assert specs[0].windows == (5.0, 30.0) and specs[0].mode == "rate"
    monkeypatch.setenv("RAFIKI_SLO", "[{malformed")
    assert _specs_from_env() is None  # falls back to defaults...
    errors = [r for r in journal_mod.read_dir(journal.log_dir)
              if r["kind"] == "slo" and r["name"] == "config_error"]
    assert errors  # ...and says so in the journal


# -- profiler collector ------------------------------------------------------


def test_profiler_collector_joins_cost_and_steps(counters, journaled):
    from rafiki_tpu.obs.perf import profiler

    profiler.reset()
    try:
        key = ("test_prog", "x")
        profiler.note_epoch(key, 0.5, cold=True)
        for _ in range(4):
            profiler.note_epoch(key, 0.012, feed_s=0.002)
        snap = telemetry.snapshot()
        assert "perf" in snap  # registered collector rides the snapshot
        progs = snap["perf"]["programs"]
        summary = progs[profiler.key_hash(key)]
        assert summary["epochs"] == 4 and summary["cold_epochs"] == 1
        assert summary["step_p50_s"] == pytest.approx(0.010)
        steps = [r for r in journal_mod.read_dir(journal.log_dir)
                 if r["kind"] == "perf" and r["name"] == "step"]
        assert len(steps) == 5
        assert sum(r["cold"] for r in steps) == 1
    finally:
        profiler.reset()


def test_profiler_anomaly_charges_badput(counters, journaled):
    from rafiki_tpu.obs import ledger as ledger_mod
    from rafiki_tpu.obs.perf import profiler

    profiler.reset()
    try:
        key = ("test_prog", "badput")
        for _ in range(12):
            profiler.note_epoch(key, 0.01)
        report = profiler.note_epoch(key, 0.5)
        assert report is not None and report["ratio"] > 10
        snap = telemetry.snapshot()
        assert snap["counters"].get("perf.anomalies") == 1
        assert snap["goodput"]["total"].get("badput_s", 0.0) == pytest.approx(
            0.49, abs=0.01)
        anomalies = [r for r in journal_mod.read_dir(journal.log_dir)
                     if r["kind"] == "perf" and r["name"] == "anomaly"]
        assert len(anomalies) == 1 and anomalies[0]["phase"] == "step"
        assert "badput_s" in ledger_mod.BUCKETS
    finally:
        profiler.reset()


# -- bench_report gate -------------------------------------------------------


def _round(n, headline, error=None):
    payload = {"metric": "m", "value": headline.get("trials_per_hour"),
               "headline": headline}
    if error:
        payload["error"] = error
    return {"n": n, "cmd": "bench", "rc": 1 if error else 0,
            "tail": [], "parsed": payload}


def _run_report(tmp_path, rounds, extra_args=()):
    paths = []
    for doc in rounds:
        p = tmp_path / f"BENCH_r{doc['n']:02d}.json"
        p.write_text(json.dumps(doc))
        paths.append(str(p))
    proc = subprocess.run(
        [sys.executable, BENCH_REPORT, *paths, *extra_args],
        capture_output=True, text=True, timeout=60)
    return proc.returncode, json.loads(proc.stdout)


HEAD = {"trials_per_hour": 1200.0, "canonical_trial_s": 3.0,
        "compile_s": 12.0, "train_img_per_s": 45000.0}


def test_bench_report_flat_history_passes(tmp_path):
    drift = dict(HEAD, trials_per_hour=1150.0)  # within 10% band
    rc, rep = _run_report(tmp_path, [_round(1, HEAD), _round(2, drift)])
    assert rc == 0
    assert rep["verdict"] == "ok"
    assert rep["metrics"]["trials_per_hour"]["verdict"] == "flat"


def test_bench_report_gates_on_regression(tmp_path):
    bad = dict(HEAD, trials_per_hour=400.0, canonical_trial_s=9.0)
    rc, rep = _run_report(tmp_path, [_round(1, HEAD), _round(2, bad)])
    assert rc == 1
    assert rep["verdict"] == "regressed"
    assert set(rep["regressed"]) == {"trials_per_hour", "canonical_trial_s"}
    assert rep["metrics"]["trials_per_hour"]["delta_frac"] == pytest.approx(
        2.0 / 3.0, abs=1e-3)


def test_bench_report_lower_better_improvement(tmp_path):
    better = dict(HEAD, canonical_trial_s=2.0, compile_s=13.0)
    rc, rep = _run_report(tmp_path, [_round(1, HEAD), _round(2, better)])
    assert rc == 0
    assert rep["metrics"]["canonical_trial_s"]["verdict"] == "improved"
    assert rep["metrics"]["compile_s"]["verdict"] == "flat"


def test_bench_report_error_rounds_are_no_data(tmp_path):
    """r03-r05 shape: an error payload with value 0.0 must not read as
    a 100% regression against the one real round."""
    dead = _round(3, {"trials_per_hour": 0.0}, error="backend unavailable")
    rc, rep = _run_report(tmp_path, [_round(1, HEAD), dead])
    assert rc == 0
    assert rep["metrics"]["trials_per_hour"]["verdict"] == "single-point"
    assert rep["rounds"][1]["has_data"] is False


def test_bench_report_backfills_pre_schema_artifacts(tmp_path):
    """A round with no headline block (schema 1) trends via the
    value/detail fallbacks — r02's real shape."""
    old = {"n": 1, "cmd": "bench", "rc": 0, "tail": [], "parsed": {
        "metric": "m", "value": 1200.0,
        "detail": {"canonical_trial_s": 3.0, "compile_s": 12.0,
                   "train_img_per_s": 45000.0}}}
    p = tmp_path / "BENCH_r01.json"
    p.write_text(json.dumps(old))
    new = tmp_path / "BENCH_r02.json"
    new.write_text(json.dumps(_round(2, dict(HEAD, trials_per_hour=390.0))))
    proc = subprocess.run(
        [sys.executable, BENCH_REPORT, str(p), str(new)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    rep = json.loads(proc.stdout)
    assert "trials_per_hour" in rep["regressed"]


def test_bench_report_tolerance_flag(tmp_path):
    bad = dict(HEAD, trials_per_hour=700.0)  # -42%
    rc, _ = _run_report(tmp_path, [_round(1, HEAD), _round(2, bad)],
                        extra_args=("--tolerance", "0.5"))
    assert rc == 0


def test_bench_report_real_history_is_green():
    """The committed BENCH_r01-r05 artifacts: one measurable round,
    four no-data rounds — the gate must hold at rc 0."""
    proc = subprocess.run([sys.executable, BENCH_REPORT],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout[:500]
    rep = json.loads(proc.stdout)
    assert rep["verdict"] == "ok"
    assert rep["metrics"]["trials_per_hour"]["n_measured"] >= 1
