"""Trial packing: k same-program trials vmapped into one XLA program.

The contract under test (ISSUE 4, docs/trial_packing.md):
  * parity — a k=4 pack produces per-trial scores matching 4 serial
    trials (same seeds, same shuffle order, same rng chains);
  * cache hygiene — packed program keys never collide with unpacked
    keys, and LRU eviction with a live PackedTrainLoop stays safe;
  * worker semantics — RAFIKI_TRIAL_PACK=4 still creates/marks/logs
    PER-TRIAL store rows and advisor feedback; pack=1 (the default)
    is behavior-identical to the serial loop;
  * throughput — packed wall-clock for k trials is measurably below
    k × the serial per-trial wall-clock, warm, on the same device.
"""

import numpy as np
import pytest

import rafiki_tpu.ops.train as ops_train
from rafiki_tpu import telemetry
from rafiki_tpu.models.ff import FeedForward
from rafiki_tpu.ops.train import (
    PackedTrainLoop,
    packed_program_key,
    program_cache_stats,
)

TRAIN = "synthetic://images?classes=4&n=256&w=8&h=8&c=1&seed=0"
VAL = "synthetic://images?classes=4&n=100&w=8&h=8&c=1&seed=1"

PACK_SRC = b"""
from rafiki_tpu.model.base import JaxModel
from rafiki_tpu.model.knobs import FixedKnob, FloatKnob
from rafiki_tpu.models.ff import _Mlp

class PackFF(JaxModel):
    @staticmethod
    def get_knob_config():
        return {
            "learning_rate": FloatKnob(1e-3, 3e-2, is_exp=True),
            "batch_size": FixedKnob(64),
            "epochs": FixedKnob(2),
            "seed": FixedKnob(0),
        }

    def build_module(self, num_classes, input_shape):
        return _Mlp(hidden_layers=1, hidden_units=32, num_classes=num_classes)
"""


def _ff(lr, **over):
    knobs = dict(hidden_layers=1, hidden_units=32, learning_rate=lr,
                 batch_size=64, epochs=2, seed=0)
    knobs.update(over)
    return FeedForward(**knobs)


LRS = [1e-2, 3e-3, 1e-3, 3e-2]


def _counter(name: str) -> float:
    return telemetry.snapshot()["counters"].get(name, 0.0)


# -- parity -------------------------------------------------------------------


def test_pack4_scores_match_serial():
    """The acceptance clause: per-trial scores from one k=4 pack match
    4 serial trials within tolerance (same seeds → identical shuffle
    order and rng chains; VAL sized 100 vs batch 64 so the padded-
    remainder eval path is exercised too)."""
    serial = []
    for lr in LRS:
        m = _ff(lr)
        m.train(TRAIN)
        serial.append(m.evaluate(VAL))
        m.destroy()

    models = [_ff(lr) for lr in LRS]
    keys = {repr(m.packing_key(m._prepared_dataset(TRAIN))) for m in models}
    assert len(keys) == 1, "lr must be a dynamic knob: one packing key"
    histories = FeedForward.train_packed(models, TRAIN)
    packed = FeedForward.evaluate_packed(models, VAL)

    np.testing.assert_allclose(packed, serial, atol=0.02)
    assert all(len(h) == 2 for h in histories)  # 2 epochs logged per trial
    assert all({"loss", "acc", "epoch"} <= set(h[0]) for h in histories)
    # per-trial params are serial-shaped: dump/load round-trips
    blob = models[0].dump_parameters()
    m2 = FeedForward(**models[0].knobs)
    m2.load_parameters(blob)
    assert abs(m2.evaluate(VAL) - packed[0]) < 1e-6
    for m in models:
        m.destroy()
    m2.destroy()


def test_shape_mismatch_rejected():
    a, b = _ff(1e-2), _ff(1e-3, hidden_units=64)
    ka = repr(a.packing_key(a._prepared_dataset(TRAIN)))
    kb = repr(b.packing_key(b._prepared_dataset(TRAIN)))
    assert ka != kb
    with pytest.raises(ValueError, match="packing key"):
        FeedForward.train_packed([a, b], TRAIN)


def test_python_feed_paths_match_fast_paths(monkeypatch):
    """Datasets over the HBM cap fall back to per-step host feeds (the
    serial loop double-buffers them; the packed loop fancy-indexes
    (k, batch) gathers). Both must train identically to the
    device-resident scan — prefetch reorders transfers, never math."""
    serial_fast = []
    for lr in LRS[:2]:
        m = _ff(lr)
        m.train(TRAIN)
        serial_fast.append(m.evaluate(VAL))
        m.destroy()
    monkeypatch.setenv("RAFIKI_DEVICE_DATASET_MAX_MB", "0")
    serial_slow = []
    for lr in LRS[:2]:
        m = _ff(lr)
        m.train(TRAIN)
        serial_slow.append(m.evaluate(VAL))
        m.destroy()
    np.testing.assert_allclose(serial_slow, serial_fast, atol=0.02)
    models = [_ff(lr) for lr in LRS[:2]]
    FeedForward.train_packed(models, TRAIN)
    packed_slow = FeedForward.evaluate_packed(models, VAL)
    np.testing.assert_allclose(packed_slow, serial_fast, atol=0.02)
    for m in models:
        m.destroy()


# -- program cache under packing ---------------------------------------------


def test_packed_key_never_collides_with_unpacked():
    """Structural guarantee: the packed cache key is a tagged 4-tuple,
    the unpacked key a (program_key, mesh_key, dynamic_lr) 3-tuple —
    same base key, disjoint cache entries."""
    base = ("mod", "cls", 4, (8, 8, 1), (), False)
    pk = packed_program_key(base, 4, True)
    assert pk[0] == "packed"
    assert pk != (base, ops_train.mesh_cache_key(None), True)
    # and live: a serial trial + a pack from the SAME template miss the
    # cache separately (two programs), never serve each other's entry
    ops_train.clear_program_cache()
    m = _ff(1e-2)
    m.train(TRAIN)
    serial_prog = m._loop.program
    before = program_cache_stats()
    models = [_ff(lr) for lr in LRS]
    FeedForward.train_packed(models, TRAIN)
    after = program_cache_stats()
    assert after["misses"] == before["misses"] + 1  # packed program is new
    assert models[0]._loop.packed.program is not serial_prog
    # second same-shape pack is a pure hit
    models2 = [_ff(lr, seed=0) for lr in LRS]
    FeedForward.train_packed(models2, TRAIN)
    assert program_cache_stats()["misses"] == after["misses"]
    for x in models + models2 + [m]:
        x.destroy()


def test_lru_eviction_with_live_pack_is_safe(monkeypatch):
    """Evicting a PackedProgram from the LRU must not break a live
    PackedTrainLoop: the loop holds its own reference and keeps
    training; a later same-key pack re-misses and recompiles."""
    monkeypatch.setattr(ops_train, "_PROGRAM_CACHE_CAP", 2)
    ops_train.clear_program_cache()
    from rafiki_tpu.model.dataset import dataset_utils

    ds = dataset_utils.load(TRAIN)
    models = [_ff(lr) for lr in LRS]
    FeedForward.train_packed(models, TRAIN)
    packed = models[0]._loop.packed
    # flood the cache so the packed entry is evicted
    evict0 = program_cache_stats()["evictions"]
    for units in (64, 128, 256):
        m = _ff(1e-3, hidden_units=units)
        m.train(TRAIN)
        m.destroy()
    assert program_cache_stats()["evictions"] > evict0
    # the live pack still trains and evaluates
    packed.run_epoch(ds, 64, [3, 4, 5, 6])
    scores = packed.evaluate(ds, 64)
    assert scores.shape == (4,)
    for m in models:
        m.destroy()


# -- worker integration -------------------------------------------------------


class _ScriptedAdvisor:
    """Deterministic advisor: same shape bucket, varying lr; records
    feedback order so the per-trial contract is checkable."""

    def __init__(self, knob_template):
        self._i = 0
        self._template = knob_template
        self.fed = []

    def propose(self):
        self._i += 1
        return dict(self._template, learning_rate=float(LRS[self._i % 4]))

    def propose_batch(self, n):
        return [self.propose() for _ in range(n)]

    def feedback(self, score, knobs):
        self.fed.append((round(float(score), 6), dict(knobs)))


def _mk_worker(tmp_path, trial_pack, n_trials=8, async_persist=False):
    from rafiki_tpu.model.base import load_model_class
    from rafiki_tpu.store import MetaStore, ParamsStore
    from rafiki_tpu.worker.train import TrainWorker

    store = MetaStore(tmp_path / "meta.sqlite3")
    params = ParamsStore(tmp_path / "params")
    cls = load_model_class(PACK_SRC, "PackFF")
    model = store.create_model("packff", "IMAGE_CLASSIFICATION", None,
                               PACK_SRC, "PackFF")
    job = store.create_train_job("app", "IMAGE_CLASSIFICATION", None,
                                 TRAIN, VAL, {"MODEL_TRIAL_COUNT": n_trials})
    sub = store.create_sub_train_job(job["id"], model["id"])
    adv = _ScriptedAdvisor(dict(batch_size=64, epochs=2, seed=0))
    worker = TrainWorker(store, params, sub["id"], cls, adv, TRAIN, VAL,
                         {"MODEL_TRIAL_COUNT": n_trials},
                         async_persist=async_persist, trial_pack=trial_pack)
    return store, params, worker, adv, sub


def test_worker_packed_run_keeps_per_trial_contract(tmp_path):
    store, params, worker, adv, sub = _mk_worker(tmp_path, trial_pack=4)
    rounds0 = _counter("worker.packed_rounds")
    n = worker.run()
    assert n == 8
    trials = store.get_trials_of_sub_train_job(sub["id"])
    assert len(trials) == 8
    assert all(t["status"] == "COMPLETED" for t in trials)
    assert all(t["score"] is not None and t["params_id"] for t in trials)
    # per-trial logs: a plot definition + one values entry per epoch
    for t in trials:
        entries = store.get_trial_logs(t["id"])
        assert any(e.get("type") == "plot" for e in entries)
        assert sum(e.get("type") == "values" for e in entries) == 2
    # per-trial advisor feedback, score matching the row
    assert len(adv.fed) == 8
    by_id = {round(t["score"], 6) for t in trials}
    assert {s for s, _ in adv.fed} == by_id
    # params blobs load back
    from rafiki_tpu.model.base import load_model_class

    cls = load_model_class(PACK_SRC, "PackFF")
    m = cls(**trials[0]["knobs"])
    m.load_parameters(params.load(trials[0]["params_id"]))
    assert 0.0 <= m.evaluate(VAL) <= 1.0
    assert _counter("worker.packed_rounds") >= rounds0 + 2
    assert _counter("worker.packed_trials") >= 8


def test_worker_pack1_default_is_serial(tmp_path):
    """trial_pack=1 (the default) must not touch the packed path at
    all: same rows, same feedback order, packed counters untouched."""
    store, params, worker, adv, sub = _mk_worker(tmp_path, trial_pack=1,
                                                 n_trials=3)
    assert worker.trial_pack == 1
    rounds0 = _counter("worker.packed_rounds")
    packed0 = _counter("worker.packed_trials")
    n = worker.run()
    assert n == 3
    trials = store.get_trials_of_sub_train_job(sub["id"])
    assert all(t["status"] == "COMPLETED" for t in trials)
    assert _counter("worker.packed_rounds") == rounds0
    assert _counter("worker.packed_trials") == packed0
    assert len(adv.fed) == 3


def test_worker_pack_env_default(tmp_path, monkeypatch):
    monkeypatch.setenv("RAFIKI_TRIAL_PACK", "4")
    _, _, worker, _, _ = _mk_worker(tmp_path, trial_pack=None, n_trials=1)
    assert worker.trial_pack == 4
    monkeypatch.delenv("RAFIKI_TRIAL_PACK")
    _, _, worker, _, _ = _mk_worker(tmp_path, trial_pack=None, n_trials=1)
    assert worker.trial_pack == 1


def test_packer_ineligible_under_multihost(tmp_path, monkeypatch):
    from rafiki_tpu.worker.train import PackedTrialRunner

    _, _, worker, _, _ = _mk_worker(tmp_path, trial_pack=4, n_trials=1)
    assert PackedTrialRunner(worker, 4).eligible()
    monkeypatch.setenv("RAFIKI_NUM_PROCESSES", "2")
    assert not PackedTrialRunner(worker, 4).eligible()


# -- advisor q-batch ----------------------------------------------------------


def test_propose_batch_defaults_and_gp_liar():
    from rafiki_tpu.advisor.base import make_advisor
    from rafiki_tpu.advisor.gp import GpAdvisor
    from rafiki_tpu.model.knobs import FixedKnob, FloatKnob

    kc = {"learning_rate": FloatKnob(1e-4, 1e-1, is_exp=True),
          "seed": FixedKnob(0)}
    rnd = make_advisor(kc, kind="random")
    assert len(rnd.propose_batch(4)) == 4

    gp = GpAdvisor(kc, seed=0, n_initial=4)
    for i in range(6):
        gp.feedback(float(np.sin(i)), gp.propose())
    batch = gp.propose_batch(4)
    assert len(batch) == 4
    # constant-liar diversity: the 4 picks are not duplicates
    lrs = sorted(np.log(b["learning_rate"]) for b in batch)
    assert min(b - a for a, b in zip(lrs, lrs[1:])) > 1e-6
    # lies were popped: only the 6 real observations remain
    assert len(gp._X) == 6 and len(gp._y) == 6


# -- throughput ---------------------------------------------------------------


@pytest.mark.slow
def test_pack4_beats_serial_wall_clock():
    """The perf claim, measured warm on this device: one k=4 pack is
    faster than 4 serial trials (acceptance: packed < 4 × serial
    per-trial). Marked slow — timing asserts don't belong in tier-1."""
    import time

    def serial_once():
        for lr in LRS:
            m = _ff(lr)
            m.train(TRAIN)
            m.evaluate(VAL)
            m.destroy()

    def packed_once():
        models = [_ff(lr) for lr in LRS]
        FeedForward.train_packed(models, TRAIN)
        FeedForward.evaluate_packed(models, VAL)
        for m in models:
            m.destroy()

    serial_once(), packed_once()  # warm both program paths
    t0 = time.monotonic()
    serial_once()
    serial_s = time.monotonic() - t0
    t0 = time.monotonic()
    packed_once()
    packed_s = time.monotonic() - t0
    assert packed_s < serial_s, (packed_s, serial_s)
