"""Process-per-chip scheduling: subprocess workers + HTTP advisor.

Runs real OS subprocesses (CPU platform) sharing the sqlite meta store
and a loopback advisor server — the production scheduler shape,
exercised hermetically.
"""

import threading
import time

import pytest

from rafiki_tpu.scheduler import ProcessScheduler, worker_device_env
from rafiki_tpu.store import MetaStore, ParamsStore

from tests.test_scheduler import FF_SOURCE, TRAIN, VAL


@pytest.fixture()
def env(tmp_path):
    store = MetaStore(tmp_path / "meta.sqlite3")
    params = ParamsStore(tmp_path / "params")
    model = store.create_model("tinyff", "IMAGE_CLASSIFICATION", None,
                               FF_SOURCE, "TinyFF")
    return store, params, model


def _make_job(store, model, budget):
    job = store.create_train_job("procapp", "IMAGE_CLASSIFICATION", None,
                                 TRAIN, VAL, budget)
    store.create_sub_train_job(job["id"], model["id"])
    return job


def test_device_env_cpu():
    env = worker_device_env("cpu", 0, devices_per_trial=2)
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "device_count=2" in env["XLA_FLAGS"]


def test_device_env_tpu():
    env = worker_device_env("tpu", 3, devices_per_trial=1)
    assert env["TPU_VISIBLE_CHIPS"] == "3"
    env2 = worker_device_env("tpu", 1, devices_per_trial=2)
    assert env2["TPU_VISIBLE_CHIPS"] == "2,3"


SLOW_FF_SOURCE = FF_SOURCE.replace(
    b"class TinyFF(JaxModel):",
    b"""class SlowFF(JaxModel):
    def train(self, uri):
        import time
        time.sleep(1.0)  # outlast subprocess startup skew
        super().train(uri)
""",
).replace(b'"TinyFF"', b'"SlowFF"')


def test_process_train_job(env, tmp_path):
    """BOTH subprocess workers must really run trials (budget shared
    via the sqlite atomic claim): each trial sleeps 1s, so one worker
    cannot drain the 8-trial budget during the other's startup skew
    (both spawn concurrently; skew between them is well under 8s)."""
    store, params, _ = env
    model = store.create_model("slowff", "IMAGE_CLASSIFICATION", None,
                               SLOW_FF_SOURCE, "SlowFF")
    job = _make_job(store, model, {"MODEL_TRIAL_COUNT": 8})
    sched = ProcessScheduler(store, params)
    result = sched.run_train_job(job["id"], n_workers=2,
                                 advisor_kind="random", platform="cpu")
    assert result.status == "COMPLETED", result.errors
    assert len(result.trials) == 8
    completed = [t for t in result.trials if t["status"] == "COMPLETED"]
    assert len(completed) == 8
    workers = {t["worker_id"] for t in completed}
    assert len(workers) == 2, f"budget drained by one worker: {workers}"
    # params written by the subprocess are loadable here
    best = result.best_trials[0]
    assert len(params.load(best["params_id"])) > 100


def test_workers_populate_persistent_xla_cache(env, tmp_path, monkeypatch):
    """Subprocess workers enable jax's on-disk compilation cache
    (worker/main.py): after a job, compiled executables are on disk for
    future processes to load instead of recompiling."""
    cache_dir = tmp_path / "xla-cache"
    monkeypatch.setenv("RAFIKI_XLA_CACHE_DIR", str(cache_dir))
    monkeypatch.setenv("RAFIKI_XLA_CACHE_MIN_S", "0")  # CPU compiles are fast
    store, params, model = env
    job = _make_job(store, model, {"MODEL_TRIAL_COUNT": 1})
    sched = ProcessScheduler(store, params)
    result = sched.run_train_job(job["id"], n_workers=1,
                                 advisor_kind="random", platform="cpu")
    assert result.status == "COMPLETED", result.errors
    entries = list(cache_dir.glob("*"))
    assert entries, "no persistent-cache entries written by the worker"


def test_process_job_stop_event(env):
    store, params, model = env
    # Budget must exceed what 2 workers can finish in the 10s window
    # below, or stop_event has nothing left to interrupt — with a warm
    # persistent XLA cache throughput tops 50 trials/s, so 500 was
    # within reach.
    job = _make_job(store, model, {"MODEL_TRIAL_COUNT": 5000})
    sched = ProcessScheduler(store, params)
    stop = threading.Event()
    out = {}

    def run():
        out["result"] = sched.run_train_job(job["id"], n_workers=2,
                                            advisor_kind="random",
                                            platform="cpu", stop_event=stop)

    th = threading.Thread(target=run, daemon=True)
    th.start()
    time.sleep(10)
    stop.set()
    th.join(timeout=60)
    assert not th.is_alive()
    assert out["result"].status == "STOPPED"
    assert len(out["result"].trials) < 5000


# ---------------------------------------------------------------------------
# Group liveness: a follower exiting rc=0 mid-trial (round-4 ADVICE d)
# ---------------------------------------------------------------------------


class _StubProc:
    """poll()-only stand-in for a subprocess.Popen in _WorkerGroup."""

    def __init__(self, rc):
        self._rc = rc

    def poll(self):
        return self._rc


def _group(*rcs):
    from rafiki_tpu.scheduler.process import _WorkerGroup

    g = _WorkerGroup(0)
    g.procs = [_StubProc(rc) for rc in rcs]
    return g


def test_follower_rc0_midtrial_fails_group_after_grace(monkeypatch):
    """The wedge: follower gone rc=0, leader alive. The group must go
    'failed' once the grace window elapses — not sit 'running' until
    the collective transport timeout minutes later."""
    monkeypatch.setenv("RAFIKI_FOLLOWER_EXIT_GRACE_S", "0.2")
    g = _group(None, 0)  # leader alive, follower exited clean
    assert g.state() == "running"  # first observation arms the clock
    assert g.partial_exit_at is not None
    time.sleep(0.3)
    assert g.state() == "failed"


def test_follower_rc0_within_grace_stays_running(monkeypatch):
    monkeypatch.setenv("RAFIKI_FOLLOWER_EXIT_GRACE_S", "30")
    g = _group(None, 0)
    assert g.state() == "running"
    assert g.state() == "running"  # second poll inside grace: still up


def test_clean_group_exit_is_ok_not_failed():
    g = _group(0, 0)
    assert g.state() == "ok"
    assert g.partial_exit_at is None


def test_follower_nonzero_exit_fails_immediately():
    g = _group(None, 1)  # crash path keeps its zero-delay behavior
    assert g.state() == "failed"


def test_leader_clean_exit_with_follower_draining_stays_running():
    # Leader done (budget drained), follower still flushing: normal
    # shutdown tail, must NOT arm the partial-exit clock.
    g = _group(0, None)
    assert g.state() == "running"
    assert g.partial_exit_at is None
