"""Test harness: fake an 8-chip pod on CPU.

Set platform/device-count flags BEFORE jax initialises (SURVEY.md §7
"faking the pod in CI"). Every test then sees 8 jax CPU devices, so
schedulers, meshes and collectives are exercised without TPU hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

# This image's sitecustomize force-registers a TPU PJRT plugin backend
# regardless of JAX_PLATFORMS; the explicit config update wins.
from rafiki_tpu.utils.backend import force_cpu_backend  # noqa: E402

force_cpu_backend(n_devices=8)

import pytest  # noqa: E402


@pytest.fixture()
def tmp_config(tmp_path):
    """A Config rooted in a temp dir, installed as the process default."""
    from rafiki_tpu.config import Config, set_config, get_config

    cfg = Config(data_dir=tmp_path / "rafiki")
    cfg.ensure_dirs()
    prev = get_config()
    set_config(cfg)
    yield cfg
    set_config(prev)
