"""Inference path: bus, workers, ensemble, predictor scatter/gather."""

import threading
import time

import numpy as np
import pytest

from rafiki_tpu.bus import InProcBus
from rafiki_tpu.predictor import Predictor, ensemble_predictions
from rafiki_tpu.worker.inference import InferenceWorker


def test_ensemble_mean_prob():
    p = ensemble_predictions([[0.8, 0.2], [0.6, 0.4]])
    np.testing.assert_allclose(p, [0.7, 0.3])


def test_ensemble_skips_errors():
    p = ensemble_predictions([{"error": "x"}, [0.5, 0.5]])
    np.testing.assert_allclose(p, [0.5, 0.5])


def test_ensemble_all_errors():
    p = ensemble_predictions([{"error": "x"}, {"error": "y"}])
    assert "error" in p


def test_ensemble_non_numeric_falls_back():
    assert ensemble_predictions(["NN", "VB"]) == "NN"


def test_ensemble_mismatched_shapes_falls_back():
    assert ensemble_predictions([[0.5, 0.5], [0.3, 0.3, 0.4]]) == [0.5, 0.5]


class _ConstModel:
    """Stand-in model: returns a fixed prob vector per query."""

    def __init__(self, vec):
        self.vec = list(vec)

    def predict(self, queries):
        return [self.vec for _ in queries]


def test_predictor_fan_out_gather_ensemble():
    bus = InProcBus()
    stop = threading.Event()
    w1 = InferenceWorker(bus, "job1", "w1", _ConstModel([0.9, 0.1]), stop_event=stop)
    w2 = InferenceWorker(bus, "job1", "w2", _ConstModel([0.5, 0.5]), stop_event=stop)
    t1 = threading.Thread(target=w1.run, daemon=True)
    t2 = threading.Thread(target=w2.run, daemon=True)
    t1.start(), t2.start()
    try:
        for _ in range(100):
            if len(bus.get_workers("job1")) == 2:
                break
            time.sleep(0.01)
        pred = Predictor(bus, "job1", timeout_s=5.0)
        out = pred.predict([[1.0], [2.0], [3.0]])
        assert len(out) == 3
        np.testing.assert_allclose(out[0], [0.7, 0.3])
    finally:
        stop.set()
        t1.join(timeout=2), t2.join(timeout=2)
    assert bus.get_workers("job1") == []


def test_predictor_no_workers_raises():
    bus = InProcBus()
    with pytest.raises(RuntimeError):
        Predictor(bus, "nojob").predict([[1.0]])


def test_worker_error_contained():
    class Exploding:
        def predict(self, queries):
            raise ValueError("boom")

    bus = InProcBus()
    stop = threading.Event()
    w = InferenceWorker(bus, "j", "w", Exploding(), stop_event=stop)
    t = threading.Thread(target=w.run, daemon=True)
    t.start()
    try:
        for _ in range(100):
            if bus.get_workers("j"):
                break
            time.sleep(0.01)
        out = Predictor(bus, "j", timeout_s=5.0).predict([[1.0]])
        assert "error" in out[0]
    finally:
        stop.set()
        t.join(timeout=2)


def test_mp_bus_same_interface():
    from rafiki_tpu.bus import make_mp_bus

    bus = make_mp_bus()
    bus.add_worker("j", "w1")
    assert bus.get_workers("j") == ["w1"]
    bus.add_query("w1", "q1", [1.0])
    items = bus.pop_queries("w1", timeout=1.0)
    assert items == [("q1", [1.0])]
    bus.put_prediction("q1", "w1", [0.5])
    preds = bus.get_predictions("q1", n=1, timeout=2.0)
    assert preds == [("w1", [0.5])]
    bus.remove_worker("j", "w1")
    assert bus.get_workers("j") == []
