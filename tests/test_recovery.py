"""Failure detection + orphaned-trial recovery."""

import time

import pytest

from rafiki_tpu.constants import ServiceStatus, ServiceType
from rafiki_tpu.scheduler.recovery import recover_orphaned_trials
from rafiki_tpu.store import MetaStore, ParamsStore

from tests.test_checkpoint_resume import FF3_SOURCE, TRAIN, VAL


@pytest.fixture()
def env(tmp_path):
    store = MetaStore(tmp_path / "meta.sqlite3")
    params = ParamsStore(tmp_path / "params")
    row = store.create_model("ff3", "IMAGE_CLASSIFICATION", None, FF3_SOURCE, "FF3")
    job = store.create_train_job("recapp", "IMAGE_CLASSIFICATION", None,
                                 TRAIN, VAL, {"MODEL_TRIAL_COUNT": 2})
    sub = store.create_sub_train_job(job["id"], row["id"])
    return store, params, sub


def test_orphan_detection(env):
    store, params, sub = env
    svc_live = store.create_service(ServiceType.TRAIN_WORKER.value)
    svc_dead = store.create_service(ServiceType.TRAIN_WORKER.value)
    knobs = {"learning_rate": 3e-3, "batch_size": 32, "epochs": 3}
    t_live = store.create_trial(sub["id"], "FF3", knobs, worker_id="w0",
                                service_id=svc_live["id"])
    t_dead = store.create_trial(sub["id"], "FF3", knobs, worker_id="w1",
                                service_id=svc_dead["id"])
    store.update_service(svc_dead["id"], status=ServiceStatus.ERRORED.value)
    store.update_service(svc_live["id"], heartbeat=True)

    orphans = store.get_orphaned_trials(stale_after_s=60)
    assert [t["id"] for t in orphans] == [t_dead["id"]]

    # a live trial goes stale once its service stops heartbeating
    orphans = store.get_orphaned_trials(stale_after_s=-1)  # everything stale
    assert {t["id"] for t in orphans} == {t_live["id"], t_dead["id"]}


def test_completed_trials_never_orphaned(env):
    store, params, sub = env
    svc = store.create_service(ServiceType.TRAIN_WORKER.value)
    t = store.create_trial(sub["id"], "FF3", {"epochs": 3}, service_id=svc["id"])
    store.mark_trial_as_completed(t["id"], 0.9, None)
    store.update_service(svc["id"], status=ServiceStatus.ERRORED.value)
    assert store.get_orphaned_trials(stale_after_s=-1) == []


def test_admin_recover_sync_and_background(tmp_config):
    """Admin.recover_trials: wait=True returns terminal rows; wait=False
    claims orphans (RUNNING, new owner) and finishes in background."""
    import time as _time

    from rafiki_tpu.admin import Admin

    admin = Admin(config=tmp_config)
    try:
        store = admin.store
        row = store.create_model("ff3", "IMAGE_CLASSIFICATION", None,
                                 FF3_SOURCE, "FF3")
        job = store.create_train_job("recadm", "IMAGE_CLASSIFICATION", None,
                                     TRAIN, VAL, {"MODEL_TRIAL_COUNT": 2})
        sub = store.create_sub_train_job(job["id"], row["id"])
        knobs = {"learning_rate": 3e-3, "batch_size": 32, "epochs": 3}

        def orphan():
            svc = store.create_service(ServiceType.TRAIN_WORKER.value)
            t = store.create_trial(sub["id"], "FF3", knobs, worker_id="dead",
                                   service_id=svc["id"])
            store.update_service(svc["id"], status=ServiceStatus.ERRORED.value)
            return t

        t1 = orphan()
        out = admin.recover_trials(stale_after_s=60, wait=True)
        assert [o["id"] for o in out] == [t1["id"]]
        assert out[0]["status"] == "COMPLETED"

        t2 = orphan()
        out = admin.recover_trials(stale_after_s=60, wait=False)
        assert [o["id"] for o in out] == [t2["id"]]
        deadline = _time.monotonic() + 120
        while _time.monotonic() < deadline:
            if store.get_trial(t2["id"])["status"] == "COMPLETED":
                break
            _time.sleep(0.5)
        assert store.get_trial(t2["id"])["status"] == "COMPLETED"
    finally:
        admin.stop()


def test_recover_orphaned_trial_end_to_end(env):
    """A trial whose worker died mid-run is detected and re-run to
    completion by the recovery sweep (from its checkpoint when present)."""
    store, params, sub = env
    from rafiki_tpu.model.base import load_model_class
    from rafiki_tpu.worker.train import TrainWorker

    model_row = store.get_model(sub["model_id"])
    cls = load_model_class(model_row["model_file"], "FF3")

    class Crashy(cls):  # type: ignore[misc, valid-type]
        def evaluate(self, uri):
            raise KeyboardInterrupt  # hard death: no ERRORED mark

    Crashy.__name__ = "FF3"
    svc = store.create_service(ServiceType.TRAIN_WORKER.value)
    w = TrainWorker(store, params, sub["id"], Crashy, None, TRAIN, VAL,
                    {"MODEL_TRIAL_COUNT": 2}, worker_id="dying",
                    async_persist=False, checkpoint_every=1)
    w.service_id = svc["id"]
    knobs = {"learning_rate": 3e-3, "batch_size": 32, "epochs": 3}
    with pytest.raises(KeyboardInterrupt):
        w.run_trial(knobs)
    store.update_service(svc["id"], status=ServiceStatus.ERRORED.value)

    # the trial is RUNNING with a dead service → orphan
    orphans = store.get_orphaned_trials(stale_after_s=60)
    assert len(orphans) == 1
    assert params.latest_checkpoint(orphans[0]["id"]) is not None

    results = recover_orphaned_trials(store, params, stale_after_s=60)
    assert len(results) == 1
    assert results[0]["status"] == "COMPLETED"
    assert results[0]["score"] is not None
    assert results[0]["params_id"]
    # sweep is now clean
    assert store.get_orphaned_trials(stale_after_s=60) == []


# ---------------------------------------------------------------------------
# Sweep WAL (scheduler/wal.py)
# ---------------------------------------------------------------------------


@pytest.fixture
def journaled(tmp_path):
    from rafiki_tpu.obs.journal import journal

    journal.configure(tmp_path, role="test")
    try:
        yield tmp_path
    finally:
        journal.close()


def test_wal_roundtrip_and_torn_tail(tmp_path):
    from rafiki_tpu.scheduler.wal import SweepWal, WalError, read_wal

    p = tmp_path / "wal" / "sweep-j1.wal"
    wal = SweepWal(p, generation=0)
    txn = wal.intent("budget_claim", sub_id="s1", knobs_hash="h1")
    wal.commit(txn, "budget_claim", trial_id="t1")
    wal.note("sweep_config", advisor_kind="gp", chips=2)
    wal.close()

    recs = read_wal(p)
    assert [r["rec"] for r in recs] == ["intent", "commit", "note"]
    assert recs[0]["txn"] == recs[1]["txn"] == txn
    assert recs[0]["lsn"] == 1 and recs[2]["gen"] == 0

    # A torn FINAL line (death mid-write, pre-fsync-return: the writer
    # never acted on it) is dropped silently.
    with open(p, "a") as fh:
        fh.write('{"lsn": 4, "rec": "inte')
    assert len(read_wal(p)) == 3

    # A torn INTERIOR line is corruption, not a crash artifact.
    lines = p.read_text().splitlines()
    lines[1] = lines[1][:10]
    p.write_text("\n".join(lines) + "\n")
    with pytest.raises(WalError):
        read_wal(p)


def test_wal_txn_ids_unique_across_handles(tmp_path):
    """Two handles on the same file (the resume process opens an
    adoption-phase log AND the continuation run_sweep's) must never
    collide on txn ids even though they share a pid."""
    from rafiki_tpu.scheduler.wal import SweepWal, read_wal

    p = tmp_path / "w.wal"
    a, b = SweepWal(p), SweepWal(p, generation=1)
    txns = {a.intent("budget_claim"), b.intent("budget_claim"),
            a.intent("backfill"), b.intent("backfill")}
    a.close(), b.close()
    assert len(txns) == 4
    assert len({r["txn"] for r in read_wal(p)}) == 4


def test_reconcile_proves_clean_accounting():
    from rafiki_tpu.scheduler.wal import reconcile

    trials = [{"id": "t1", "knobs": {"lr": 0.01}, "no": 1}]
    records = [
        {"rec": "intent", "op": "budget_claim", "txn": "w1-ab-1",
         "sub_id": "s1"},
        {"rec": "commit", "op": "budget_claim", "txn": "w1-ab-1",
         "trial_id": "t1"},
        {"rec": "intent", "op": "budget_claim", "txn": "w1-ab-2",
         "sub_id": "s1"},
        {"rec": "commit", "op": "budget_claim", "txn": "w1-ab-2",
         "denied": True},
    ]
    r = reconcile(records, trials, sub={"claimed": 1}, sub_id="s1")
    assert r.ok, r.errors
    assert r.claims == {"t1": 1} and r.denied == 1


def test_reconcile_catches_doctored_wal():
    """The polarity check: a committed-but-unclaimed slot (the WAL
    says a claim landed; no store row exists) must be CAUGHT."""
    from rafiki_tpu.scheduler.wal import WalReconcileError, reconcile

    records = [
        {"rec": "intent", "op": "budget_claim", "txn": "w1-cd-1"},
        {"rec": "commit", "op": "budget_claim", "txn": "w1-cd-1",
         "trial_id": "ghost"},
    ]
    r = reconcile(records, [])
    assert not r.ok
    assert {e["type"] for e in r.errors} == {"committed_unclaimed"}
    with pytest.raises(WalReconcileError, match="committed_unclaimed"):
        r.raise_if_failed()

    # ...and the inverse: a store row no WAL claim covers.
    r2 = reconcile([], [{"id": "tX", "knobs": {}, "no": 1}])
    assert {e["type"] for e in r2.errors} == {"unlogged_claim"}


def test_reconcile_resolves_in_doubt_intent_by_knobs_hash():
    from rafiki_tpu.obs.search.audit import knobs_hash
    from rafiki_tpu.scheduler.wal import reconcile

    knobs = {"learning_rate": 0.003}
    trials = [{"id": "t1", "knobs": knobs, "no": 1}]
    records = [{"rec": "intent", "op": "budget_claim", "txn": "w9-ef-1",
                "knobs_hash": knobs_hash(knobs)}]
    r = reconcile(records, trials)
    assert r.ok, r.errors
    assert r.in_doubt == [{"txn": "w9-ef-1", "op": "budget_claim",
                           "landed": True}]
    assert r.claims == {"t1": 1}


# ---------------------------------------------------------------------------
# resume_sweep (scheduler/recovery.py)
# ---------------------------------------------------------------------------


def test_resume_refuses_doctored_wal(env, journaled):
    """resume_sweep must NOT adopt a job whose WAL-vs-store accounting
    is provably wrong — compounding damage is worse than staying down."""
    from rafiki_tpu.obs.journal import read_dir
    from rafiki_tpu.scheduler.recovery import resume_sweep
    from rafiki_tpu.scheduler.wal import SweepWal, WalReconcileError, wal_path

    store, params, sub = env
    job_id = sub["train_job_id"]
    wal = SweepWal(wal_path(store.path, job_id))
    wal.note("sweep_config", advisor_kind="random", chips=1,
             trials_per_chip=1)
    txn = wal.intent("budget_claim", sub_id=sub["id"], knobs_hash="h")
    wal.commit(txn, "budget_claim", trial_id="ghost")  # doctored
    wal.close()

    with pytest.raises(WalReconcileError):
        resume_sweep(store, params, job_id, stale_after_s=60)
    recs = read_dir(journaled)
    assert any(r.get("kind") == "recovery"
               and r.get("name") == "reconcile_failed" for r in recs)


def test_resume_without_wal_degrades_loudly(env, journaled):
    from rafiki_tpu.obs.journal import read_dir
    from rafiki_tpu.scheduler.recovery import resume_sweep

    store, params, sub = env
    summary = resume_sweep(store, params, sub["train_job_id"],
                           stale_after_s=60)
    assert summary["mode"] == "orphan_only"
    recs = read_dir(journaled)
    assert any(r.get("kind") == "recovery" and r.get("name") == "no_wal"
               for r in recs), "no-WAL degrade must be journaled loudly"


def test_double_resume_adoption_is_cas(env):
    """The double-resume race: both resumers see the same orphan; the
    CAS adopt means exactly one wins and the loser backs off."""
    store, params, sub = env
    from rafiki_tpu.constants import ServiceType

    svc_dead = store.create_service(ServiceType.TRAIN_WORKER.value)
    t = store.create_trial(sub["id"], "FF3", {"epochs": 3},
                           service_id=svc_dead["id"])
    s1 = store.create_service(ServiceType.TRAIN_WORKER.value)
    s2 = store.create_service(ServiceType.TRAIN_WORKER.value)
    won1 = store.adopt_trial(t["id"], svc_dead["id"], s1["id"], "r1",
                             expected_status=t["status"])
    won2 = store.adopt_trial(t["id"], svc_dead["id"], s2["id"], "r2",
                             expected_status=t["status"])
    assert won1 and not won2
    assert store.get_trial(t["id"])["service_id"] == s1["id"]

    # A zombie worker finishing first also beats adoption: terminal
    # status never regresses to RUNNING.
    store.mark_trial_as_completed(t["id"], 0.5, None)
    s3 = store.create_service(ServiceType.TRAIN_WORKER.value)
    assert not store.adopt_trial(t["id"], s1["id"], s3["id"], "r3")
    assert store.get_trial(t["id"])["status"] == "COMPLETED"


def test_recovery_advisor_routes_adopted_scores(journaled):
    from rafiki_tpu.obs.journal import read_dir
    from rafiki_tpu.scheduler.recovery import _RecoveryAdvisor

    class Inner:
        def __init__(self):
            self.seen = []

        def feedback(self, score, knobs):
            self.seen.append((score, dict(knobs)))

    inner = Inner()
    routed = _RecoveryAdvisor(inner)
    routed.feedback(0.75, {"learning_rate": 0.01})
    assert inner.seen == [(0.75, {"learning_rate": 0.01})]

    orphan_only = _RecoveryAdvisor(None)
    orphan_only.feedback(0.25, {"learning_rate": 0.02})  # must not raise

    with pytest.raises(RuntimeError):
        routed.propose()
    with pytest.raises(RuntimeError):
        routed.propose_batch(2)

    recs = [r for r in read_dir(journaled)
            if r.get("kind") == "recovery" and r.get("name") == "feedback"]
    assert [r["routed"] for r in recs] == [True, False]
    assert all(r.get("knobs_hash") for r in recs)


# ---------------------------------------------------------------------------
# Advisor rehydration (advisor/rehydrate.py)
# ---------------------------------------------------------------------------


def _gp_knob_config():
    from rafiki_tpu.model.knobs import FixedKnob, FloatKnob

    return {"learning_rate": FloatKnob(1e-3, 3e-2, is_exp=True),
            "batch_size": FixedKnob(32), "epochs": FixedKnob(3)}


def test_rehydrated_advisor_proposes_byte_identically(journaled):
    """The equivalence contract: a rehydrated advisor's proposals are
    byte-identical to a fresh advisor fed the same observations —
    REGARDLESS of the order the crashed process's rows are replayed in
    (rehydrate sorts them canonically)."""
    import json

    from rafiki_tpu.advisor.rehydrate import rehydrate_advisor
    from rafiki_tpu.advisor.service import AdvisorService

    obs = [({"learning_rate": lr, "batch_size": 32, "epochs": 3}, score)
           for lr, score in ((0.001, 0.4), (0.004, 0.7),
                             (0.012, 0.55), (0.028, 0.3))]

    ref = AdvisorService()
    aid_ref = ref.create_advisor(_gp_knob_config(), kind="gp", seed=7,
                                 engine_kwargs={"n_initial": 4})
    for kn, score in obs:
        ref.feedback(aid_ref, score, kn)
    want = ref.propose_batch(aid_ref, 3)

    rows = [{"id": f"t{i}", "no": i + 1, "knobs": kn, "score": score,
             "status": "COMPLETED"} for i, (kn, score) in enumerate(obs)]
    rows.reverse()  # crashed-process row order must not matter
    re = AdvisorService()
    aid = rehydrate_advisor(re, _gp_knob_config(), "gp", "dead-advisor-id",
                            completed=rows, seed=7,
                            engine_kwargs={"n_initial": 4})
    assert aid == "dead-advisor-id"
    got = re.propose_batch(aid, 3)

    assert json.dumps(got, sort_keys=True) == json.dumps(want, sort_keys=True)


def test_rehydrate_supplements_from_advisor_journals(journaled):
    """Scores the store never saw as completed rows (doomed-trial
    consolation feedback) come back from the kind="advisor" journals:
    feedback joined to its propose by knobs_hash."""
    from rafiki_tpu.advisor.rehydrate import journal_observations
    from rafiki_tpu.obs.search.audit import knobs_hash

    k1 = {"learning_rate": 0.002, "batch_size": 32, "epochs": 3}
    k2 = {"learning_rate": 0.009, "batch_size": 32, "epochs": 3}
    records = [
        {"kind": "advisor", "name": "propose", "advisor_id": "a1",
         "knobs": k1, "knobs_hash": knobs_hash(k1)},
        {"kind": "advisor", "name": "propose", "advisor_id": "a1",
         "knobs": k2, "knobs_hash": knobs_hash(k2)},
        {"kind": "advisor", "name": "feedback", "advisor_id": "a1",
         "knobs_hash": knobs_hash(k1), "score": 0.6},
        {"kind": "advisor", "name": "feedback", "advisor_id": "a1",
         "knobs_hash": knobs_hash(k2), "score": 0.8},
        # another advisor's records never bleed in
        {"kind": "advisor", "name": "feedback", "advisor_id": "OTHER",
         "knobs_hash": knobs_hash(k1), "score": 0.0},
    ]
    got = journal_observations(records, advisor_id="a1")
    assert sorted(s for _, s in got) == [0.6, 0.8]
    # store-covered hashes are excluded (the store row wins)
    got = journal_observations(records, advisor_id="a1",
                               exclude_hashes={knobs_hash(k1)})
    assert [s for _, s in got] == [0.8]


# ---------------------------------------------------------------------------
# Dead-supervisor detection + services-manager reaper
# ---------------------------------------------------------------------------


def test_dead_supervisor_detection(env):
    from rafiki_tpu.constants import TrainJobStatus

    store, params, sub = env
    job_id = sub["train_job_id"]
    store.update_train_job_status(job_id, TrainJobStatus.RUNNING.value)
    assert store.get_jobs_with_dead_supervisor(60) == []  # no supervisor row

    store.create_service(ServiceType.SUPERVISOR.value, job_id=job_id,
                         worker_index=0)
    assert store.get_jobs_with_dead_supervisor(60) == []  # fresh heartbeat
    time.sleep(0.15)
    dead = store.get_jobs_with_dead_supervisor(0.1)
    assert [j["id"] for j in dead] == [job_id]

    # A live next-generation supervisor clears the alarm.
    store.create_service(ServiceType.SUPERVISOR.value, job_id=job_id,
                         worker_index=1)
    assert store.get_jobs_with_dead_supervisor(0.1) == []


def test_reaper_detects_and_resumes_dead_supervisor(env, journaled):
    from rafiki_tpu.admin.services_manager import ServicesManager
    from rafiki_tpu.constants import TrainJobStatus
    from rafiki_tpu.obs.journal import read_dir

    store, params, sub = env
    job_id = sub["train_job_id"]
    store.update_train_job_status(job_id, TrainJobStatus.RUNNING.value)
    store.create_service(ServiceType.SUPERVISOR.value, job_id=job_id,
                         worker_index=0)
    time.sleep(0.15)

    sm = ServicesManager(store, params)
    sm.start_resume_reaper(poll_s=0.05, stale_after_s=0.1)
    try:
        deadline = time.monotonic() + 15
        seen = set()
        while time.monotonic() < deadline:
            seen = {r.get("name") for r in read_dir(journaled)
                    if r.get("kind") == "recovery"
                    and r.get("job_id") == job_id}
            if {"reaper_detected", "resume_started"} <= seen:
                break
            time.sleep(0.05)
        assert {"reaper_detected", "resume_started"} <= seen, seen
    finally:
        sm.stop_resume_reaper()
    # idempotent stop/start
    sm.start_resume_reaper(poll_s=10, stale_after_s=10)
    sm.start_resume_reaper(poll_s=10, stale_after_s=10)
    sm.stop_all()


# ---------------------------------------------------------------------------
# Chaos acceptance scenarios (slow: full subprocess sweeps)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_supervisor_kill_mid_sweep_acceptance():
    """ISSUE 15 acceptance: SIGKILLed sweep resumes in a fresh process
    with (a) best score equal to an unfaulted run under the same
    seeds, (b) zero double-claimed slots by WAL reconcile, (c) a
    non-warmup post-resume propose_batch in the audit journals."""
    from rafiki_tpu.chaos.runner import run_scenario

    report = run_scenario("supervisor-kill-mid-sweep")
    assert report.passed, "\n".join(
        f"{c.name}: {c.detail}" for c in report.checks if not c.ok) \
        + (f"\n{report.error}" if report.error else "")
    names = {c.name for c in report.checks}
    assert {"best_score_matches_unfaulted", "no_double_claims",
            "post_resume_batch_non_warmup",
            "obs_resume_reconstructs"} <= names


@pytest.mark.slow
def test_host_loss_mid_sweep_acceptance():
    from rafiki_tpu.chaos.runner import run_scenario

    report = run_scenario("host-loss-mid-sweep")
    assert report.passed, "\n".join(
        f"{c.name}: {c.detail}" for c in report.checks if not c.ok) \
        + (f"\n{report.error}" if report.error else "")
    names = {c.name for c in report.checks}
    assert {"survivors_repacked", "wal_reconciles_clean"} <= names
