"""Failure detection + orphaned-trial recovery."""

import time

import pytest

from rafiki_tpu.constants import ServiceStatus, ServiceType
from rafiki_tpu.scheduler.recovery import recover_orphaned_trials
from rafiki_tpu.store import MetaStore, ParamsStore

from tests.test_checkpoint_resume import FF3_SOURCE, TRAIN, VAL


@pytest.fixture()
def env(tmp_path):
    store = MetaStore(tmp_path / "meta.sqlite3")
    params = ParamsStore(tmp_path / "params")
    row = store.create_model("ff3", "IMAGE_CLASSIFICATION", None, FF3_SOURCE, "FF3")
    job = store.create_train_job("recapp", "IMAGE_CLASSIFICATION", None,
                                 TRAIN, VAL, {"MODEL_TRIAL_COUNT": 2})
    sub = store.create_sub_train_job(job["id"], row["id"])
    return store, params, sub


def test_orphan_detection(env):
    store, params, sub = env
    svc_live = store.create_service(ServiceType.TRAIN_WORKER.value)
    svc_dead = store.create_service(ServiceType.TRAIN_WORKER.value)
    knobs = {"learning_rate": 3e-3, "batch_size": 32, "epochs": 3}
    t_live = store.create_trial(sub["id"], "FF3", knobs, worker_id="w0",
                                service_id=svc_live["id"])
    t_dead = store.create_trial(sub["id"], "FF3", knobs, worker_id="w1",
                                service_id=svc_dead["id"])
    store.update_service(svc_dead["id"], status=ServiceStatus.ERRORED.value)
    store.update_service(svc_live["id"], heartbeat=True)

    orphans = store.get_orphaned_trials(stale_after_s=60)
    assert [t["id"] for t in orphans] == [t_dead["id"]]

    # a live trial goes stale once its service stops heartbeating
    orphans = store.get_orphaned_trials(stale_after_s=-1)  # everything stale
    assert {t["id"] for t in orphans} == {t_live["id"], t_dead["id"]}


def test_completed_trials_never_orphaned(env):
    store, params, sub = env
    svc = store.create_service(ServiceType.TRAIN_WORKER.value)
    t = store.create_trial(sub["id"], "FF3", {"epochs": 3}, service_id=svc["id"])
    store.mark_trial_as_completed(t["id"], 0.9, None)
    store.update_service(svc["id"], status=ServiceStatus.ERRORED.value)
    assert store.get_orphaned_trials(stale_after_s=-1) == []


def test_admin_recover_sync_and_background(tmp_config):
    """Admin.recover_trials: wait=True returns terminal rows; wait=False
    claims orphans (RUNNING, new owner) and finishes in background."""
    import time as _time

    from rafiki_tpu.admin import Admin

    admin = Admin(config=tmp_config)
    try:
        store = admin.store
        row = store.create_model("ff3", "IMAGE_CLASSIFICATION", None,
                                 FF3_SOURCE, "FF3")
        job = store.create_train_job("recadm", "IMAGE_CLASSIFICATION", None,
                                     TRAIN, VAL, {"MODEL_TRIAL_COUNT": 2})
        sub = store.create_sub_train_job(job["id"], row["id"])
        knobs = {"learning_rate": 3e-3, "batch_size": 32, "epochs": 3}

        def orphan():
            svc = store.create_service(ServiceType.TRAIN_WORKER.value)
            t = store.create_trial(sub["id"], "FF3", knobs, worker_id="dead",
                                   service_id=svc["id"])
            store.update_service(svc["id"], status=ServiceStatus.ERRORED.value)
            return t

        t1 = orphan()
        out = admin.recover_trials(stale_after_s=60, wait=True)
        assert [o["id"] for o in out] == [t1["id"]]
        assert out[0]["status"] == "COMPLETED"

        t2 = orphan()
        out = admin.recover_trials(stale_after_s=60, wait=False)
        assert [o["id"] for o in out] == [t2["id"]]
        deadline = _time.monotonic() + 120
        while _time.monotonic() < deadline:
            if store.get_trial(t2["id"])["status"] == "COMPLETED":
                break
            _time.sleep(0.5)
        assert store.get_trial(t2["id"])["status"] == "COMPLETED"
    finally:
        admin.stop()


def test_recover_orphaned_trial_end_to_end(env):
    """A trial whose worker died mid-run is detected and re-run to
    completion by the recovery sweep (from its checkpoint when present)."""
    store, params, sub = env
    from rafiki_tpu.model.base import load_model_class
    from rafiki_tpu.worker.train import TrainWorker

    model_row = store.get_model(sub["model_id"])
    cls = load_model_class(model_row["model_file"], "FF3")

    class Crashy(cls):  # type: ignore[misc, valid-type]
        def evaluate(self, uri):
            raise KeyboardInterrupt  # hard death: no ERRORED mark

    Crashy.__name__ = "FF3"
    svc = store.create_service(ServiceType.TRAIN_WORKER.value)
    w = TrainWorker(store, params, sub["id"], Crashy, None, TRAIN, VAL,
                    {"MODEL_TRIAL_COUNT": 2}, worker_id="dying",
                    async_persist=False, checkpoint_every=1)
    w.service_id = svc["id"]
    knobs = {"learning_rate": 3e-3, "batch_size": 32, "epochs": 3}
    with pytest.raises(KeyboardInterrupt):
        w.run_trial(knobs)
    store.update_service(svc["id"], status=ServiceStatus.ERRORED.value)

    # the trial is RUNNING with a dead service → orphan
    orphans = store.get_orphaned_trials(stale_after_s=60)
    assert len(orphans) == 1
    assert params.latest_checkpoint(orphans[0]["id"]) is not None

    results = recover_orphaned_trials(store, params, stale_after_s=60)
    assert len(results) == 1
    assert results[0]["status"] == "COMPLETED"
    assert results[0]["score"] is not None
    assert results[0]["params_id"]
    # sweep is now clean
    assert store.get_orphaned_trials(stale_after_s=60) == []
