"""Closed-loop elasticity (docs/autoscale.md): the controller's
decision table on a fake clock, the drain→reap→freed ordering contract
on a real bus, pre-warmed compiled packs, and the elastic mesh lane.

Everything here is deterministic by construction — injectable clocks,
explicit seeds, stub actuators where real capacity isn't the point."""

import json
import threading
import time

import pytest

from rafiki_tpu import telemetry
from rafiki_tpu.autoscale.controller import (AutoscaleController, LaneSpec,
                                             inference_pressure,
                                             read_sensors, sweep_pressure)


class StubLane:
    def __init__(self, n=2):
        self.n = n
        self.calls = []

    def size(self):
        return self.n

    def scale_to(self, n):
        self.calls.append(n)
        self.n = n


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _controller(lane, sensor_fn, clock, **kw):
    kw.setdefault("seed", 0)
    kw.setdefault("tick_s", 1.0)
    kw.setdefault("tick_global_slo", False)
    return AutoscaleController(
        lanes=[LaneSpec("inference", min_size=1, max_size=8,
                        up_threshold=1.0, down_threshold=0.3,
                        up_cooldown_s=5.0, down_cooldown_s=30.0)],
        sensor_fn=sensor_fn,
        actuators={"inference": lane},
        clock=clock, **kw)


def _burn(level):
    return {"slo_breaching": ["x"] if level else [],
            "slo_burn": level, "queue_frac": 0.0, "shed_rate": 0.0}


# ---------------------------------------------------------------------------
# decision table
# ---------------------------------------------------------------------------


def test_hysteresis_band_holds_between_thresholds():
    lane, clock = StubLane(), FakeClock()
    ctl = _controller(lane, lambda: _burn(0.6), clock)
    (d,) = ctl.tick()
    assert d.direction == "hold" and d.reason == "in-band"
    assert lane.calls == []


def test_pressure_above_threshold_scales_up_one_step():
    lane, clock = StubLane(), FakeClock()
    ctl = _controller(lane, lambda: _burn(2.0), clock)
    (d,) = ctl.tick()
    assert d.direction == "up" and d.actuated and d.target == 3
    assert lane.calls == [3]


def test_idle_pressure_scales_down_one_step():
    lane, clock = StubLane(4), FakeClock()
    ctl = _controller(lane, lambda: _burn(0.0), clock)
    (d,) = ctl.tick()
    assert d.direction == "down" and d.actuated and d.target == 3


def test_clamped_at_bounds():
    lane, clock = StubLane(8), FakeClock()
    ctl = _controller(lane, lambda: _burn(2.0), clock)
    (d,) = ctl.tick()
    assert d.direction == "hold" and d.reason == "at-max"
    lane2 = StubLane(1)
    ctl2 = _controller(lane2, lambda: _burn(0.0), clock)
    (d2,) = ctl2.tick()
    assert d2.direction == "hold" and d2.reason == "at-min"
    assert lane.calls == lane2.calls == []


def test_same_direction_cooldown_blocks_then_releases():
    lane, clock = StubLane(), FakeClock()
    ctl = _controller(lane, lambda: _burn(2.0), clock)
    assert ctl.tick()[0].actuated
    clock.t = 2.0  # inside the 5s up cooldown
    (held,) = ctl.tick()
    assert held.direction == "hold" and held.reason == "cooldown"
    clock.t = 6.0  # past it
    assert ctl.tick()[0].actuated
    assert lane.calls == [3, 4]


def test_cooldowns_are_per_direction():
    """A fresh scale-up must not block a scale-down: each direction
    rate-limits itself (the flap GUARD is what gates the flip, and it
    has its own, shorter clock)."""
    lane, clock = StubLane(4), FakeClock()
    signal = {"v": 2.0}
    ctl = _controller(lane, lambda: _burn(signal["v"]), clock,
                      flap_guard_s=1.0)
    assert ctl.tick()[0].direction == "up"
    signal["v"] = 0.0
    clock.t = 2.0  # inside up's 5s cooldown, past the 1s flap guard
    (d,) = ctl.tick()
    assert d.direction == "down" and d.actuated, d.reason


def test_flap_damping_converges_where_undamped_oscillates():
    def square_wave():
        state = {"i": 0}

        def fn():
            state["i"] += 1
            return _burn(2.0 if state["i"] % 2 else 0.0)
        return fn

    def run(damping):
        lane, clock = StubLane(), FakeClock()
        ctl = AutoscaleController(
            lanes=[LaneSpec("inference", min_size=1, max_size=8,
                            up_threshold=1.0, down_threshold=0.3,
                            up_cooldown_s=1.0, down_cooldown_s=1.0)],
            sensor_fn=square_wave(),
            actuators={"inference": lane},
            clock=clock, seed=0, tick_s=2.0, damping=damping,
            flap_window_s=600.0, flap_flips=2, flap_backoff=2.0,
            flap_guard_s=2.0, flap_guard_cap_s=64.0,
            tick_global_slo=False)
        for _ in range(100):
            ctl.tick()
            clock.t += 2.0
        return len(lane.calls)

    undamped, damped = run(False), run(True)
    assert undamped >= 50, "square wave should thrash an undamped loop"
    assert damped <= undamped // 3
    assert damped <= 30


def test_twin_pregate_veto_blocks_actuation():
    lane, clock = StubLane(), FakeClock()
    seen = []

    def pregate(lane_name, current, target, sensors):
        seen.append((lane_name, current, target))
        return {"veto": True, "p99_ms_delta": +40.0}

    ctl = _controller(lane, lambda: _burn(2.0), clock, pregate_fn=pregate)
    (d,) = ctl.tick()
    assert d.vetoed and not d.actuated and d.direction == "up"
    assert d.forecast["p99_ms_delta"] == 40.0
    assert seen == [("inference", 2, 3)]
    assert lane.calls == []


def test_sensor_error_holds_every_lane():
    lane, clock = StubLane(), FakeClock()

    def broken():
        raise RuntimeError("sensor plane down")

    ctl = _controller(lane, broken, clock)
    before = telemetry.get_counter("autoscale.sensor_errors")
    (d,) = ctl.tick()
    assert d.direction == "hold" and d.reason == "sensor-error"
    assert lane.calls == []
    assert telemetry.get_counter("autoscale.sensor_errors") == before + 1


def test_decision_stream_is_byte_deterministic():
    """Same clock script, same seed, same sensors -> byte-identical
    decision dicts (the replay contract `obs autoscale` leans on)."""

    def run():
        lane, clock = StubLane(), FakeClock()
        state = {"i": 0}

        def sensors():
            state["i"] += 1
            return _burn([2.0, 0.0, 0.6, 2.0][state["i"] % 4])

        ctl = _controller(lane, sensors, clock)
        out = []
        for _ in range(12):
            out.extend(d.to_dict() for d in ctl.tick())
            clock.t += 3.0
        return json.dumps(out, sort_keys=True)

    assert run() == run()


def test_actuator_failure_still_arms_cooldown():
    class FailingLane(StubLane):
        def scale_to(self, n):
            raise RuntimeError("spawn failed")

    lane, clock = FailingLane(), FakeClock()
    ctl = _controller(lane, lambda: _burn(2.0), clock)
    (d,) = ctl.tick()
    assert not d.actuated and "spawn failed" in d.sensors["actuate_error"]
    clock.t = 2.0
    (held,) = ctl.tick()
    assert held.reason == "cooldown", \
        "a broken actuator retried every tick is its own flap"


def test_pressure_functions():
    p, why = inference_pressure({"slo_breaching": ["x"], "slo_burn": 1.4,
                                 "queue_frac": 0.2, "shed_rate": 0.01})
    assert p == 1.4 and why == "slo_burn"
    p, why = inference_pressure({"slo_breaching": [], "slo_burn": 9.0,
                                 "queue_frac": 0.2, "shed_rate": 0.0})
    assert p == 0.2 and why == "queue_frac", "burn only counts breaching"
    assert sweep_pressure({}) == (None, "no-target")


def test_sweep_pressure_from_env(monkeypatch):
    monkeypatch.setenv("RAFIKI_AUTOSCALE_TARGET_EPH", "100")
    assert sweep_pressure({"effective_trials_per_hour": None}) == \
        (None, "no-data")
    p, why = sweep_pressure({"effective_trials_per_hour": 50.0})
    assert p == 2.0 and why == "eph"


def test_lane_spec_from_env(monkeypatch):
    monkeypatch.setenv("RAFIKI_AUTOSCALE_MAX", "3")
    monkeypatch.setenv("RAFIKI_AUTOSCALE_UP_COOLDOWN_S", "9.5")
    spec = LaneSpec.from_env("inference", min_size=2)
    assert (spec.max_size, spec.up_cooldown_s, spec.min_size) == (3, 9.5, 2)


def test_read_sensors_merges_gateway_and_slo():
    from rafiki_tpu.obs.perf.slo import SloEngine, SloSpec

    engine = SloEngine([SloSpec("x", "gauge:autoscale.test_gauge", 1.0)],
                       tick_s=0.0)
    s = read_sensors(slo_engine=engine)
    assert not s["slo_breaching"] and s["slo_burn"] == 0.0
    assert "effective_trials_per_hour" in s


# ---------------------------------------------------------------------------
# drain→reap→freed ordering (the scale-down correctness contract)
# ---------------------------------------------------------------------------


class _SlowModel:
    """Holds each forward long enough that a drain provably overlaps
    inflight work."""

    def __init__(self, hold_s=0.2):
        self.hold_s = hold_s

    def predict(self, queries):
        time.sleep(self.hold_s)
        return [[0.5, 0.5] for _ in queries]


def _spawned_worker(bus, job, wid, model):
    from rafiki_tpu.worker.inference import InferenceWorker

    stop = threading.Event()
    w = InferenceWorker(bus, job, wid, model, stop_event=stop)
    th = threading.Thread(target=w.run, daemon=True)
    th.start()
    return w, th


def test_drain_flushes_inflight_then_reaps_then_frees():
    from rafiki_tpu.autoscale.actuators import InferenceWorkerLane
    from rafiki_tpu.bus import InProcBus

    bus, job = InProcBus(), "drainjob"
    lane = InferenceWorkerLane(
        bus, job,
        spawn_fn=lambda i: (f"as{i}",) + _spawned_worker(
            bus, job, f"as{i}", _SlowModel()))
    lane.scale_to(2)
    assert lane.size() == 2 and sorted(lane.worker_ids()) == ["as0", "as1"]
    # Park a query on the victim (newest = as1) and wait until its
    # serve loop has POPPED it — the drain now overlaps real inflight
    # work, not an empty queue.
    bus.add_query("as1", "q-inflight", [1.0])
    deadline = time.monotonic() + 5
    while bus.queue_depth("as1") > 0:
        assert time.monotonic() < deadline, "query never popped"
        time.sleep(0.005)
    lane.scale_to(1)
    # The inflight reply was published BEFORE the slot was counted
    # freed: the prediction must exist now, with zero further wait.
    preds = bus.get_predictions("q-inflight", 1, timeout=0.0)
    assert preds and preds[0][1] == [0.5, 0.5]
    assert [e for e in lane.events if e[1] == "as1"] == \
        [("drained", "as1"), ("reaped", "as1"), ("freed", "as1")]
    assert "as1" not in bus.get_workers(job)
    assert lane.size() == 1 and lane.worker_ids() == ["as0"]
    lane.scale_to(0)


def test_drain_timeout_on_stuck_worker_is_counted():
    """A victim whose lease never leaves the bus must not wedge the
    lane forever: the bounded wait expires, the timeout is counted,
    and the slot is still reclaimed (the janitor owns the corpse)."""
    from rafiki_tpu.autoscale.actuators import InferenceWorkerLane
    from rafiki_tpu.bus import InProcBus

    class _Corpse:
        def stop(self):
            pass  # ignores the drain — and holds no drained event

    bus, job = InProcBus(), "stuckjob"
    bus.add_worker(job, "w0")
    bus.add_worker(job, "w1")
    lane = InferenceWorkerLane(
        bus, job, spawn_fn=lambda i: (_ for _ in ()).throw(AssertionError),
        initial=[("w0", _Corpse(), None), ("w1", _Corpse(), None)],
        drain_timeout_s=0.2)
    before = telemetry.get_counter("autoscale.drain_timeouts")
    lane.scale_to(1)
    assert telemetry.get_counter("autoscale.drain_timeouts") == before + 1
    assert lane.size() == 1


# ---------------------------------------------------------------------------
# pre-warmed compiled packs
# ---------------------------------------------------------------------------


def test_probe_knobs_picks_midpoints():
    from rafiki_tpu.autoscale.prewarm import probe_knobs
    from rafiki_tpu.model.knobs import (CategoricalKnob, FixedKnob,
                                        FloatKnob, IntegerKnob)

    probe = probe_knobs({
        "fixed": FixedKnob(32),
        "cat": CategoricalKnob([8, 16]),
        "int": IntegerKnob(2, 10),
        "lin": FloatKnob(0.0, 1.0),
        "exp": FloatKnob(1e-4, 1e-2, is_exp=True),
    })
    assert probe["fixed"] == 32 and probe["cat"] == 8 and probe["int"] == 6
    assert probe["lin"] == pytest.approx(0.5)
    assert probe["exp"] == pytest.approx(1e-3)


@pytest.mark.slow
def test_prewarm_primes_the_program_cache(tmp_path, monkeypatch):
    """A prewarmed packing key must make the NEXT PackedTrainLoop for
    the same key a program-cache hit — that hit is the 12.8s compile
    scale-up no longer pays."""
    monkeypatch.setenv("RAFIKI_XLA_CACHE_DIR", str(tmp_path / "xla"))
    from rafiki_tpu.autoscale.prewarm import prewarm_models, probe_knobs
    from rafiki_tpu.chaos.scenarios import FF_SOURCE, TRAIN
    from rafiki_tpu.model.base import load_model_class

    cls = load_model_class(FF_SOURCE, "ChaosFF")
    probe = probe_knobs(cls.get_knob_config())
    first = prewarm_models(cls, [probe, probe], TRAIN, k=2)
    assert first["errors"] == []
    assert first["warmed"] == 1 and first["keys"] == 1
    second = prewarm_models(cls, [probe, probe], TRAIN, k=2)
    assert second["errors"] == []
    assert second["cache_hits"] == 1, \
        "the second prewarm of the same packing key must hit the cache"


# ---------------------------------------------------------------------------
# elastic mesh lane
# ---------------------------------------------------------------------------


def test_elastic_handle_bookkeeping():
    from rafiki_tpu.scheduler.mesh import ElasticHandle

    h = ElasticHandle()
    h._set_live(2)
    assert h.desired() == 2 and h.live() == 2
    h.request(2)
    h.request(-1)
    assert h.desired() == 3
    assert h._take() == 1
    assert h._take() == 0, "the delta is consumed exactly once"
    h._set_live(3)
    h.request(-99)
    assert h.desired() == 0, "desired never goes negative"


def test_sweep_chip_lane_requests_deltas():
    from rafiki_tpu.autoscale.actuators import SweepChipLane
    from rafiki_tpu.scheduler.mesh import ElasticHandle

    h = ElasticHandle()
    h._set_live(2)
    lane = SweepChipLane(h)
    assert lane.size() == 2
    lane.scale_to(4)
    assert h.desired() == 4
    lane.scale_to(4)  # no-op: desired already matches
    assert h._take() == 2


@pytest.fixture()
def mesh_env(tmp_path):
    from rafiki_tpu.store import MetaStore, ParamsStore

    store = MetaStore(tmp_path / "meta.sqlite3")
    params = ParamsStore(tmp_path / "params")
    return store, params


def _mesh_job(store, budget):
    from rafiki_tpu.chaos.scenarios import FF_SOURCE, TRAIN, VAL

    model = store.create_model("chaosff", "IMAGE_CLASSIFICATION", None,
                               FF_SOURCE, "ChaosFF")
    job = store.create_train_job("scaleapp", "IMAGE_CLASSIFICATION", None,
                                 TRAIN, VAL, budget)
    store.create_sub_train_job(job["id"], model["id"])
    return job


@pytest.mark.slow
def test_mesh_sweep_grown_chip_is_a_first_class_survivor(mesh_env,
                                                         monkeypatch):
    """Grow mid-sweep, then lose the ORIGINAL chip: the grown chip must
    inherit the re-packed rows like any survivor — elastic capacity is
    not a second-class spectator."""
    from rafiki_tpu.chaos import FaultPlane, install, uninstall
    from rafiki_tpu.scheduler import MeshSweepScheduler
    from rafiki_tpu.scheduler.mesh import ElasticHandle

    store, params = mesh_env
    monkeypatch.setenv("RAFIKI_CHECKPOINT_EVERY", "1")
    job = _mesh_job(store, {"MODEL_TRIAL_COUNT": 2})
    telemetry.reset()
    elastic = ElasticHandle()
    elastic.request(1)  # armed before the run: applied at first poll
    install(FaultPlane.from_spec(
        "seed=11;scheduler.preempt:kill:after=2:times=1:match=chip0"))
    try:
        result = MeshSweepScheduler(store, params).run_sweep(
            job["id"], chips=1, trials_per_chip=2, advisor_kind="random",
            elastic=elastic)
    finally:
        uninstall()
    assert result.status == "COMPLETED", result.errors
    assert len(result.trials) == 2
    assert all(t["status"] == "COMPLETED" for t in result.trials)
    assert telemetry.get_counter("mesh.chips_scaled_up") >= 1.0
    assert telemetry.get_counter("mesh.chips_lost") >= 1.0
    assert any(a["dir"] == "up" for a in elastic.applied)
    # The grown chip really trained: the dead chip's rows finished
    # under its worker id.
    assert any((t["worker_id"] or "").endswith("-mesh-c1")
               for t in result.trials)


@pytest.mark.slow
def test_mesh_sweep_shrinks_without_charging_downtime(mesh_env,
                                                      monkeypatch):
    """A voluntary scale-down is not a failure: the victim chip drains
    at its epoch boundary, its trials re-pack onto survivors, and
    neither ``mesh.chips_lost`` nor the downtime ledger is charged."""
    from rafiki_tpu.obs.ledger import ledger
    from rafiki_tpu.scheduler import MeshSweepScheduler
    from rafiki_tpu.scheduler.mesh import ElasticHandle

    store, params = mesh_env
    monkeypatch.setenv("RAFIKI_CHECKPOINT_EVERY", "1")
    job = _mesh_job(store, {"MODEL_TRIAL_COUNT": 4})
    telemetry.reset()
    ledger.reset()
    elastic = ElasticHandle()
    elastic.request(-1)
    result = MeshSweepScheduler(store, params).run_sweep(
        job["id"], chips=2, trials_per_chip=2, advisor_kind="random",
        elastic=elastic)
    assert result.status == "COMPLETED", result.errors
    assert len(result.trials) == 4, "shrink lost or duplicated trials"
    assert all(t["status"] == "COMPLETED" for t in result.trials)
    assert telemetry.get_counter("mesh.chips_scaled_down") >= 1.0
    assert telemetry.get_counter("mesh.chips_lost") == 0.0, \
        "a voluntary shrink must not masquerade as a chip loss"
    assert any(a["dir"] == "down" for a in elastic.applied)
    downtime = ledger.snapshot()["total"].get("downtime_s", 0.0)
    assert downtime == 0.0, \
        f"voluntary shrink charged {downtime}s downtime"


# ---------------------------------------------------------------------------
# gateway sensor surface + CLI replay
# ---------------------------------------------------------------------------


def test_gateway_sensors_shape():
    from rafiki_tpu.bus import InProcBus
    from rafiki_tpu.gateway import Gateway, GatewayConfig
    from rafiki_tpu.predictor import Predictor

    gw = Gateway(Predictor(InProcBus(), "sensorjob"),
                 GatewayConfig(max_queue=10))
    s = gw.sensors()
    assert s["queue_depth"] == 0 and s["queue_frac"] == 0.0
    assert s["inflight"] == 0 and s["shed_rate"] == 0.0
    assert s["draining"] is False and s["breakers_open"] == 0


def test_obs_autoscale_check_catches_undamped_flap(tmp_path, capsys):
    from rafiki_tpu.obs.cli import cmd_autoscale
    from rafiki_tpu.obs.journal import journal

    def run(damping, sub):
        d = tmp_path / sub
        journal.configure(d, role="test")
        try:
            lane, clock = StubLane(), FakeClock()
            state = {"i": 0}

            def sensors():
                state["i"] += 1
                return _burn(2.0 if state["i"] % 2 else 0.0)

            ctl = AutoscaleController(
                lanes=[LaneSpec("inference", min_size=1, max_size=8,
                                up_threshold=1.0, down_threshold=0.3,
                                up_cooldown_s=1.0, down_cooldown_s=1.0)],
                sensor_fn=sensors, actuators={"inference": lane},
                clock=clock, seed=0, tick_s=2.0, damping=damping,
                flap_window_s=600.0, flap_flips=2, flap_backoff=2.0,
                flap_guard_s=2.0, flap_guard_cap_s=64.0,
                tick_global_slo=False)
            for _ in range(60):
                ctl.tick()
                clock.t += 2.0
        finally:
            journal.close()
        return str(d)

    undamped = run(False, "undamped")
    damped = run(True, "damped")
    assert cmd_autoscale(undamped, 0, False, True, 60.0, 4) == 1
    assert "FLAPPING" in capsys.readouterr().err
    assert cmd_autoscale(damped, 0, False, True, 60.0, 4) == 0
    # An empty dir is an error, not a silent pass.
    empty = tmp_path / "empty"
    empty.mkdir()
    assert cmd_autoscale(str(empty), 0, False, True, 60.0, 4) == 1
