"""Serve-path elasticity: the predictor must survive inference-worker
death mid-serving, matching the train path's SIGKILL coverage
(tests/test_elastic.py).

The serving unit killed here is a real OS process — the deployment
shape the reference gets from one-container-per-trial (SURVEY.md
§3.2) — running ``run_inference_worker_process`` over the mp bus.
SIGKILL means the worker's ``remove_worker`` cleanup never runs, so
its bus registration outlives it; liveness is the heartbeat lease
(bus/queues.py): the predictor stops fanning out to (and waiting on)
the corpse within one lease TTL and the ensemble degrades to k-1.
"""

import multiprocessing as mp
import os
import signal
import time

import numpy as np
import pytest

from rafiki_tpu.bus import make_mp_bus
from rafiki_tpu.predictor.predictor import Predictor
from rafiki_tpu.scheduler import LocalScheduler
from rafiki_tpu.store import MetaStore, ParamsStore
from rafiki_tpu.worker.inference import run_inference_worker_process

from tests.test_scheduler import FF_SOURCE, TRAIN, VAL

JOB = "serve-elastic"
TIMEOUT_S = 3.0   # predictor batch gather deadline
# Liveness lease: 8x the 0.5s heartbeat period, so several missed
# beats on a loaded CI host can't expire a LIVE worker's lease (the
# old 4x margin flaked under manager-proxy latency spikes, and 6x
# still left the post-SIGKILL freshness window too tight — the
# corpse's last beat races the kill).
TTL_S = 4.0
HEARTBEAT_S = 0.5  # must match InferenceWorker.HEARTBEAT_S


def _ok(out):
    return all(not (isinstance(o, dict) and "error" in o) for o in out)


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    """Two trained trials served by THREE real worker processes (the
    third re-serves trial 0: k=3 gives the quorum test a majority to
    gather after its straggler is SIGKILLed)."""
    tmp = tmp_path_factory.mktemp("serve")
    store = MetaStore(tmp / "meta.sqlite3")
    params = ParamsStore(tmp / "params")
    model = store.create_model("tinyff", "IMAGE_CLASSIFICATION", None,
                               FF_SOURCE, "TinyFF")
    job = store.create_train_job("app", "IMAGE_CLASSIFICATION", None,
                                 TRAIN, VAL, {"MODEL_TRIAL_COUNT": 2})
    store.create_sub_train_job(job["id"], model["id"])
    result = LocalScheduler(store, params).run_train_job(
        job["id"], n_workers=1, advisor_kind="random")
    best = result.best_trials[:2]
    assert len(best) == 2

    ctx = mp.get_context("spawn")
    bus = make_mp_bus(ctx.Manager())
    trials = [best[0], best[1], best[0]]
    procs = [
        ctx.Process(
            target=run_inference_worker_process,
            args=(bus, str(tmp / "meta.sqlite3"), str(tmp / "params"),
                  t["id"], JOB, f"iw-{i}"),
            daemon=True)
        for i, t in enumerate(trials)
    ]
    for p in procs:
        p.start()
    deadline = time.monotonic() + 120
    while len(bus.get_workers(JOB)) < len(procs):
        # Fail FAST on a dead child instead of burning the whole
        # registration deadline: the round-5 regression (spawn target
        # missing honor_env_platform, child hung/died in backend init)
        # cost 120s per run before reporting anything.
        dead = [(p.name, p.exitcode) for p in procs if not p.is_alive()]
        assert not dead, f"worker process died before registering: {dead}"
        assert time.monotonic() < deadline, "workers never registered"
        time.sleep(0.05)
    yield bus, procs
    for p in procs:
        if p.is_alive():
            p.kill()


def test_quorum_gather_survives_sigkilled_straggler(served):
    """SIGKILL one of k=3 worker processes mid-load: while its lease is
    still FRESH (the predictor has no liveness signal yet), a quorum
    gather through the gateway must keep answering within the deadline
    — p99 tracks the surviving majority, not the corpse — and the
    corpse's circuit breaker must start recording misses."""
    from rafiki_tpu.gateway import Gateway, GatewayConfig

    bus, procs = served
    pred = Predictor(bus, JOB, timeout_s=TIMEOUT_S, worker_ttl_s=TTL_S)
    rng = np.random.default_rng(1)
    queries = list(rng.uniform(0, 1, size=(4, 8, 8, 1)).astype(np.float32))

    # Warm until every worker answers within one deadline (first
    # forward pays each subprocess's XLA compile): wait-for-all gather
    # succeeding means all 3 replied in time.
    deadline = time.monotonic() + 120
    while True:
        report = pred.predict_detailed(queries)
        if _ok(report.outputs) and len(report.replies) == len(procs):
            break
        assert time.monotonic() < deadline, "serving never warmed"
        time.sleep(0.5)

    gateway = Gateway(pred, GatewayConfig(
        max_inflight=4, min_replies=2, hedge_grace_s=0.2,
        default_deadline_s=TIMEOUT_S))

    # SIGKILL the straggler-to-be mid-load; its lease stays fresh for
    # up to TTL_S, during which only the quorum keeps us fast.
    os.kill(procs[2].pid, signal.SIGKILL)
    procs[2].join(10)
    assert not procs[2].is_alive()
    # Deadline-poll instead of a single check (the round-5 ADVICE
    # flake): a manager-proxy read can transiently miss a worker whose
    # lease is in fact fresh, so retry briefly before declaring the
    # quorum window lost.
    deadline = time.monotonic() + 1.0
    while "iw-2" not in bus.get_workers(JOB, max_age_s=TTL_S):
        assert time.monotonic() < deadline, \
            "corpse lease expired before the quorum window was exercised"
        time.sleep(0.05)

    for _ in range(3):
        t0 = time.monotonic()
        out = gateway.predict(queries)
        dt = time.monotonic() - t0
        assert _ok(out), f"quorum batch failed: {out[:2]}"
        assert dt < TIMEOUT_S, \
            f"quorum gather waited on the SIGKILLed straggler ({dt:.1f}s)"
    stats = gateway.stats()
    assert stats["timeouts"] == 0
    assert stats["breakers"]["iw-2"]["failures"] >= 1


def test_sigkilled_inference_worker_degrades_to_k_minus_1(served):
    bus, procs = served
    pred = Predictor(bus, JOB, timeout_s=TIMEOUT_S, worker_ttl_s=TTL_S)
    rng = np.random.default_rng(0)
    # Shape must match TRAIN: synthetic images default to c=1, so the
    # trained MLP flattens 8*8*1=64 features — 3-channel queries would
    # shape-error in every worker and the warm loop could never pass.
    queries = list(rng.uniform(0, 1, size=(8, 8, 8, 1)).astype(np.float32))

    # Warm until BOTH workers answer within the deadline (first forward
    # pays each subprocess's XLA compile).
    deadline = time.monotonic() + 120
    while not _ok(pred.predict(queries)):
        assert time.monotonic() < deadline, "serving never warmed"
        time.sleep(0.5)

    # SIGKILL one worker mid-serving: no cleanup, registration leaks.
    os.kill(procs[0].pid, signal.SIGKILL)
    procs[0].join(10)
    assert not procs[0].is_alive()

    # The very next batch must still answer (k-1 ensemble), bounded by
    # ONE batch deadline — the corpse costs at most timeout_s once.
    t0 = time.monotonic()
    out = pred.predict(queries)
    dt = time.monotonic() - t0
    assert _ok(out), f"post-kill batch failed: {out[:2]}"
    assert dt < TIMEOUT_S + 2.0, f"post-kill batch took {dt:.1f}s"

    # Once the lease expires the corpse is dropped from fan-out
    # entirely: batches stop paying the gather timeout at all. Poll to
    # a deadline instead of one sleep+assert: the exact expiry moment
    # depends on the corpse's LAST heartbeat, which raced the SIGKILL.
    deadline = time.monotonic() + TTL_S * 4
    while bus.get_workers(JOB, max_age_s=TTL_S) != ["iw-1"]:
        assert time.monotonic() < deadline, \
            "dead worker still holds a fresh lease"
        time.sleep(0.1)
    t0 = time.monotonic()
    out = pred.predict(queries)
    dt = time.monotonic() - t0
    assert _ok(out)
    assert dt < TIMEOUT_S, \
        f"lease-expired corpse still stalls the gather ({dt:.1f}s)"

    # The survivor keeps serving at full quality: responses are prob
    # vectors over the 5 synthetic classes.
    assert len(out) == len(queries)
    assert all(len(np.asarray(o)) == 5 for o in out)


def _bus_squatter(bus, job, worker_id, beat_s):
    """Spawn target: register on the bus and heartbeat until killed —
    the minimal process whose SIGKILL leaves a corpse registration."""
    bus.add_worker(job, worker_id)
    while True:
        bus.heartbeat(job, worker_id)
        time.sleep(beat_s)


def test_sigkilled_worker_corpse_reaped_by_get_workers_janitor():
    """Janitor regression (bus/queues.py): a SIGKILLed worker never
    runs remove_worker, so its registration, lease timestamp and
    pending-query queue persist. Once its lease is REAP_FACTOR×TTL old,
    an ordinary ``get_workers(ttl)`` read must reap all three — no
    explicit reap_stale call anywhere."""
    ttl = 0.3
    ctx = mp.get_context("spawn")
    bus = make_mp_bus(ctx.Manager())
    job = "reap-job"
    p = ctx.Process(target=_bus_squatter, args=(bus, job, "corpse", 0.05),
                    daemon=True)
    p.start()
    deadline = time.monotonic() + 60
    while "corpse" not in bus.get_workers(job):
        assert p.is_alive(), f"squatter died (exit {p.exitcode})"
        assert time.monotonic() < deadline, "squatter never registered"
        time.sleep(0.02)

    # A pending fan-out the corpse will never pop: the janitor must
    # delete this queue too, or corpse queues grow under churn.
    bus.add_query("corpse", "q-leak", [1.0])
    assert bus.queue_depth("corpse") == 1

    os.kill(p.pid, signal.SIGKILL)
    p.join(10)
    assert not p.is_alive()

    # Only lease-filtered reads run the janitor; the unfiltered read
    # shows whether the REGISTRATION still exists (vs merely being
    # hidden by the TTL filter).
    deadline = time.monotonic() + 30
    while "corpse" in bus.get_workers(job):
        bus.get_workers(job, max_age_s=ttl)  # the observing read
        assert time.monotonic() < deadline, \
            "janitor never reaped the SIGKILLed worker's registration"
        time.sleep(0.05)
    assert bus.queue_depth("corpse") == 0, "corpse queue outlived the reap"
    assert f"{job}|corpse" not in dict(bus._worker_ts), \
        "corpse lease timestamp outlived the reap"
