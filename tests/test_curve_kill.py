"""Learning-curve early-kill + speculative scoring (docs/early_kill.md).

The contract under test:
  * **off polarity is bit-exact** — with both ``RAFIKI_CURVE_KILL`` and
    ``RAFIKI_CURVE_SPECULATE`` off, ``CurveCoordinator.from_env()`` is
    None, a disabled coordinator threaded through a GP loop leaves the
    proposal stream byte-identical to a loop with no coordinator at
    all, and the journal carries zero curve-plane records;
  * **serial kill end to end** — a doomed trial dies at the first
    eligible epoch boundary with an ERRORED row, a predicted-score
    consolation feedback charged to the doomed bucket, and
    ``advisor/predict`` + ``advisor/kill`` records that reconcile;
  * **speculation** — in-flight curves are fed to the engine exactly
    once in sorted-hash order, a later real score journals the
    correction, and PR 15 rehydration replays uncorrected speculations
    to byte-identical proposals (and would diverge without them).
"""

import json
import math

import pytest

from rafiki_tpu.advisor.curve import KillConfig, fit_curve
from rafiki_tpu.advisor.speculative import CurveCoordinator
from rafiki_tpu.model.knobs import FixedKnob, FloatKnob, IntegerKnob
from rafiki_tpu.obs.journal import journal, read_dir
from rafiki_tpu.obs.search.ledger import search_ledger

CURVE_RECORD_NAMES = {"predict", "kill", "speculate", "correct",
                      "false_kill"}


@pytest.fixture
def journaled(tmp_path):
    search_ledger.reset()
    journal.configure(tmp_path, role="test")
    try:
        yield tmp_path
    finally:
        journal.close()
        search_ledger.reset()


def _knob_config():
    return {"lr": FloatKnob(1e-4, 1e-1, is_exp=True),
            "units": IntegerKnob(4, 64),
            "b": FixedKnob(8)}


def _saturating(final, e, tau=2.0):
    return final * (1.0 - math.exp(-(e + 1) / tau))


def _curve_records(log_dir):
    return [r for r in read_dir(log_dir)
            if r.get("kind") == "advisor"
            and r.get("name") in CURVE_RECORD_NAMES]


# -- config + fit ------------------------------------------------------------


def test_from_env_off_is_none(monkeypatch):
    for var in ("RAFIKI_CURVE_KILL", "RAFIKI_CURVE_SPECULATE"):
        monkeypatch.delenv(var, raising=False)
    assert CurveCoordinator.from_env() is None
    monkeypatch.setenv("RAFIKI_CURVE_KILL", "1")
    coord = CurveCoordinator.from_env()
    assert coord is not None and coord.config.enabled
    assert not coord.config.speculate
    monkeypatch.delenv("RAFIKI_CURVE_KILL")
    monkeypatch.setenv("RAFIKI_CURVE_SPECULATE", "1")
    coord = CurveCoordinator.from_env()
    assert coord is not None and coord.config.speculate
    assert not coord.config.enabled


def test_fit_extrapolates_saturating_curve():
    pts = [(e, _saturating(0.9, e)) for e in range(6)]
    fit = fit_curve(pts, 16)
    assert fit is not None
    assert abs(fit.predicted_final - 0.9) < 0.1
    assert fit.lo <= fit.predicted_final <= fit.hi
    rec = fit.to_record()
    for key in ("family", "decay", "n_obs", "rmse", "predicted",
                "band", "lo", "hi", "horizon"):
        assert key in rec, key


def test_should_kill_gates_warmup_minobs_best_and_margin():
    cfg = KillConfig(enabled=True, warmup_epochs=2, margin=0.1, min_obs=3)
    low = fit_curve([(e, _saturating(0.15, e)) for e in range(3)], 16)
    assert low is not None and low.hi < 0.3
    assert not cfg.should_kill(low, epoch=0, best_so_far=0.9)  # warmup
    assert not cfg.should_kill(low, epoch=2, best_so_far=None)  # no best
    short = fit_curve([(e, _saturating(0.15, e)) for e in range(2)], 16)
    if short is not None:  # min_obs
        assert not cfg.should_kill(short, epoch=4, best_so_far=0.9)
    assert cfg.should_kill(low, epoch=2, best_so_far=0.9)
    assert not cfg.should_kill(low, epoch=2, best_so_far=low.hi + 0.05)


# -- off polarity is bit-exact -----------------------------------------------


def test_disabled_coordinator_leaves_gp_stream_byte_identical(journaled):
    """The regression pin for `RAFIKI_CURVE_KILL` off: threading a
    disabled coordinator through the ask/tell loop must not change one
    byte of the proposal stream, and must journal nothing."""
    from rafiki_tpu.advisor.gp import GpAdvisor

    kc = _knob_config()

    def _stream(coord):
        adv = GpAdvisor(kc, seed=11, n_initial=3)
        out = []
        for t in range(5):
            knobs = adv.propose()
            out.append(knobs)
            score = 0.5 + 0.1 * math.sin(t)
            if coord is not None:
                for e in range(4):
                    coord.observe(knobs, e, _saturating(score, e))
                    assert coord.kill_verdict(knobs, e) is None
                assert coord.speculate_inflight(adv) == 0
            adv.feedback(score, knobs)
            if coord is not None:
                coord.note_scored(knobs, score)
        return json.dumps(out, sort_keys=True)

    plain = _stream(None)
    threaded = _stream(CurveCoordinator(KillConfig()))  # both knobs off
    assert plain == threaded
    journal.close()
    assert _curve_records(journaled) == []


# -- serial worker kill end to end -------------------------------------------


class _Recorder:
    """Advisor handle that scripts proposals and records feedback."""

    def __init__(self, finals):
        self.finals = list(finals)
        self.feedbacks = []

    def propose(self):
        return {"final": self.finals.pop(0), "epochs": 6}

    def feedback(self, score, knobs):
        self.feedbacks.append((knobs["final"], score))


from rafiki_tpu.model.base import BaseModel


class _CurveModel(BaseModel):
    """Logs a saturating acc curve toward its ``final`` knob."""

    def __init__(self, final, epochs):
        from rafiki_tpu.model.log import logger

        super().__init__(final=final, epochs=epochs)
        self.final, self.epochs, self._logger = final, epochs, logger

    @staticmethod
    def get_knob_config():
        return {"final": FloatKnob(0.05, 0.95), "epochs": FixedKnob(6)}

    def train(self, uri):
        for e in range(self.epochs):
            self._logger.log(epoch=e, acc=_saturating(self.final, e),
                             loss=1.0 - _saturating(self.final, e))

    def evaluate(self, uri):
        return self.final

    def predict(self, queries):
        return []

    def dump_parameters(self):
        return b"params"

    def destroy(self):
        pass


def _worker(tmp_path, advisor, monkeypatch, kill):
    from rafiki_tpu.store import MetaStore, ParamsStore
    from rafiki_tpu.worker.train import TrainWorker

    for var in ("RAFIKI_CURVE_KILL", "RAFIKI_CURVE_SPECULATE"):
        monkeypatch.delenv(var, raising=False)
    if kill:
        monkeypatch.setenv("RAFIKI_CURVE_KILL", "1")
    store = MetaStore(tmp_path / "meta.sqlite3")
    params = ParamsStore(tmp_path / "params")
    mrow = store.create_model("curvekill", "T", None, b"x = 1", "X")
    job = store.create_train_job("app", "T", None, "t", "v", {})
    store.create_sub_train_job(job["id"], mrow["id"])
    sub = store.get_sub_train_jobs(job["id"])[0]
    worker = TrainWorker(store, params, sub["id"], _CurveModel, advisor,
                         "t", "v", {}, worker_id="curve-w0",
                         async_persist=False)
    return store, worker


def test_serial_worker_kills_doomed_trial(journaled, monkeypatch):
    adv = _Recorder([0.9, 0.1])
    store, worker = _worker(journaled, adv, monkeypatch, kill=True)
    healthy = worker.run_trial(adv.propose())
    doomed = worker.run_trial(adv.propose())
    assert healthy["status"] == "COMPLETED" and healthy["score"] == 0.9
    assert doomed["status"] == "ERRORED"
    assert "early_killed" in (doomed.get("error") or "")
    # Consolation feedback carries the conservative PREDICTED score —
    # below best by construction of the kill rule — not a 0.0 floor.
    assert adv.feedbacks[0] == (0.9, 0.9)
    killed_final, consolation = adv.feedbacks[1]
    assert killed_final == 0.1 and 0.0 < consolation < 0.9 - 0.02
    journal.close()
    recs = _curve_records(journaled)
    kills = [r for r in recs if r["name"] == "kill"]
    assert len(kills) == 1
    # First eligible boundary: warmup=2 and min_obs=3 meet at epoch 2.
    assert kills[0]["epoch"] == 2
    assert kills[0]["best_so_far"] == 0.9
    assert any(r["name"] == "predict" for r in recs)
    # The scripted handle bypasses record_feedback, so the doomed
    # bucket isn't charged here (the sweep smoke's A/B pins that);
    # the kill counter rides record_kill and must land regardless.
    assert search_ledger.snapshot()["n_killed"] == 1


def test_serial_worker_off_polarity_completes_everything(journaled,
                                                         monkeypatch):
    adv = _Recorder([0.9, 0.1])
    store, worker = _worker(journaled, adv, monkeypatch, kill=False)
    assert worker.curve is None
    assert worker.run_trial(adv.propose())["status"] == "COMPLETED"
    assert worker.run_trial(adv.propose())["status"] == "COMPLETED"
    assert [s for _, s in adv.feedbacks] == [0.9, 0.1]
    journal.close()
    assert _curve_records(journaled) == []
    assert search_ledger.snapshot()["n_killed"] == 0


# -- speculation + rehydration -----------------------------------------------


class _SpecSink:
    def __init__(self):
        self.calls = []

    def speculate(self, score, knobs, fit=None):
        self.calls.append((score, dict(knobs), fit))


def test_speculate_inflight_sorted_once_and_retired(journaled):
    from rafiki_tpu.obs.search.audit import knobs_hash

    coord = CurveCoordinator(KillConfig(speculate=True, min_obs=2))
    a, b, young = {"lr": 0.01}, {"lr": 0.02}, {"lr": 0.03}
    for e in range(3):
        coord.observe(a, e, _saturating(0.8, e))
        coord.observe(b, e, _saturating(0.6, e))
    coord.observe(young, 0, 0.1)  # below min_obs: not fed
    sink = _SpecSink()
    assert coord.speculate_inflight(sink) == 2
    fed = [knobs_hash(k) for _, k, _ in sink.calls]
    assert fed == sorted(fed)
    assert all(f is not None and "predicted" in f for *_, f in sink.calls)
    # Once per hash, and a retired curve is never speculated again.
    assert coord.speculate_inflight(sink) == 0
    coord.note_scored(a, 0.8)
    coord.note_done(b)
    coord.observe(a, 3, 0.79)
    coord.observe(b, 3, 0.59)
    assert coord.speculate_inflight(sink) == 0
    # Journaling rides the advisor's speculate() path (record_speculate
    # in advisor/base.py) — pinned by the correction test below.


def test_feedback_after_speculation_journals_correction(journaled):
    from rafiki_tpu.advisor.rehydrate import journal_speculations
    from rafiki_tpu.advisor.service import AdvisorService

    svc = AdvisorService()
    aid = svc.create_advisor(_knob_config(), kind="gp",
                             engine_kwargs={"n_initial": 2}, seed=0)
    k = svc.propose_batch(aid, 3)
    svc.feedback(aid, 0.8, k[0])
    svc.speculate(aid, 0.55, k[2])
    svc.feedback(aid, 0.61, k[2])  # the truth lands: correction
    journal.close()
    recs = read_dir(journaled)
    corrections = [r for r in recs if r.get("kind") == "advisor"
                   and r.get("name") == "correct"]
    assert len(corrections) == 1
    assert corrections[0]["predicted"] == 0.55
    assert corrections[0]["actual"] == 0.61
    assert abs(corrections[0]["error"] - 0.06) < 1e-9
    # Corrected speculations are no longer in flight for rehydration.
    assert journal_speculations(recs) == []
    assert search_ledger.snapshot()["n_corrections"] == 1


def test_journal_speculations_uncorrected_last_wins_sorted():
    from rafiki_tpu.advisor.rehydrate import journal_speculations
    from rafiki_tpu.obs.search.audit import knobs_hash

    k1, k2, k3 = {"lr": 0.01}, {"lr": 0.02}, {"lr": 0.03}
    recs = [
        {"kind": "advisor", "name": "speculate", "knobs": k1,
         "knobs_hash": knobs_hash(k1), "predicted": 0.4},
        {"kind": "advisor", "name": "speculate", "knobs": k1,
         "knobs_hash": knobs_hash(k1), "predicted": 0.45},  # last wins
        {"kind": "advisor", "name": "speculate", "knobs": k2,
         "knobs_hash": knobs_hash(k2), "predicted": 0.6},
        {"kind": "advisor", "name": "feedback",
         "knobs_hash": knobs_hash(k2), "score": 0.62},  # corrected
        {"kind": "advisor", "name": "speculate", "knobs": k3,
         "knobs_hash": knobs_hash(k3), "predicted": 0.7},
        {"kind": "event", "name": "noise"},
    ]
    out = journal_speculations(recs)
    assert [(p, knobs_hash(kn)) for kn, p, _ in out] == sorted(
        [(0.45, knobs_hash(k1)), (0.7, knobs_hash(k3))],
        key=lambda t: t[1])
    assert journal_speculations(
        recs, exclude_hashes={knobs_hash(k1)}) == [(k3, 0.7, None)]


def test_rehydration_replays_speculation_byte_identically(journaled):
    """The PR 15 contract with a speculation in flight: rehydrating
    from journals equals a fresh advisor hand-fed the same real-then-
    speculative sequence, byte for byte — and dropping the speculation
    changes the proposals, so the replay is load-bearing."""
    from rafiki_tpu.advisor.rehydrate import rehydrate_advisor
    from rafiki_tpu.advisor.service import AdvisorService

    kc = _knob_config()
    svc = AdvisorService()
    aid = svc.create_advisor(kc, kind="gp",
                             engine_kwargs={"n_initial": 2}, seed=0)
    k = svc.propose_batch(aid, 3)
    svc.feedback(aid, 0.8, k[0])
    svc.feedback(aid, 0.5, k[1])
    svc.speculate(aid, 0.72, k[2])  # still in flight at the "crash"
    journal.close()
    recs = read_dir(journaled)

    def _batch(service):
        return json.dumps(service.propose_batch(aid, 2), sort_keys=True)

    hydrated = []
    for _ in range(2):
        s = AdvisorService()
        rehydrate_advisor(s, kc, "gp", aid, completed=[],
                          journal_records=recs, seed=0,
                          engine_kwargs={"n_initial": 2})
        hydrated.append(_batch(s))
    assert hydrated[0] == hydrated[1]

    manual = AdvisorService()
    manual.create_advisor(kc, kind="gp", seed=0, advisor_id=aid,
                          engine_kwargs={"n_initial": 2})
    manual.feedback(aid, 0.8, k[0])
    manual.feedback(aid, 0.5, k[1])
    manual.speculate(aid, 0.72, k[2])
    assert _batch(manual) == hydrated[0]

    unspeculated = AdvisorService()
    rehydrate_advisor(
        unspeculated, kc, "gp", aid, completed=[],
        journal_records=[r for r in recs if r.get("name") != "speculate"],
        seed=0, engine_kwargs={"n_initial": 2})
    assert _batch(unspeculated) != hydrated[0]
