import numpy as np
import pytest

from rafiki_tpu.model.knobs import (
    CategoricalKnob,
    FixedKnob,
    FloatKnob,
    IntegerKnob,
    deserialize_knob_config,
    knob_config_signature,
    sample_knobs,
    serialize_knob_config,
    validate_knobs,
)


def _config():
    return {
        "layers": IntegerKnob(1, 3, affects_shape=True),
        "units": CategoricalKnob([32, 64], affects_shape=True),
        "lr": FloatKnob(1e-4, 1e-1, is_exp=True),
        "epochs": FixedKnob(2),
    }


def test_serialization_round_trip():
    cfg = _config()
    s = serialize_knob_config(cfg)
    cfg2 = deserialize_knob_config(s)
    assert cfg == cfg2


def test_sampling_respects_bounds():
    rng = np.random.default_rng(0)
    cfg = _config()
    for _ in range(200):
        knobs = sample_knobs(cfg, rng)
        validate_knobs(cfg, knobs)
        assert 1 <= knobs["layers"] <= 3
        assert knobs["units"] in (32, 64)
        assert 1e-4 <= knobs["lr"] <= 1e-1
        assert knobs["epochs"] == 2


def test_log_scale_sampling_covers_decades():
    rng = np.random.default_rng(0)
    k = FloatKnob(1e-4, 1e-1, is_exp=True)
    vals = [k.sample(rng) for _ in range(500)]
    assert sum(v < 1e-3 for v in vals) > 50  # log-uniform, not uniform
    assert sum(v > 1e-2 for v in vals) > 50


def test_validate_rejects_bad_values():
    cfg = _config()
    with pytest.raises(ValueError):
        validate_knobs(cfg, {"layers": 7, "units": 32, "lr": 1e-3})
    with pytest.raises(ValueError):
        validate_knobs(cfg, {"layers": 2, "units": 48, "lr": 1e-3})
    with pytest.raises(ValueError):
        validate_knobs(cfg, {"layers": 2, "units": 32, "lr": 1e-3, "bogus": 1})


def test_fixed_knob_filled_in():
    cfg = _config()
    knobs = validate_knobs(cfg, {"layers": 2, "units": 32, "lr": 1e-3})
    assert knobs["epochs"] == 2


def test_shape_signature_groups_static_knobs():
    cfg = _config()
    a = {"layers": 2, "units": 32, "lr": 1e-3, "epochs": 2}
    b = {"layers": 2, "units": 32, "lr": 5e-2, "epochs": 2}  # only lr differs
    c = {"layers": 3, "units": 32, "lr": 1e-3, "epochs": 2}
    assert knob_config_signature(cfg, a) == knob_config_signature(cfg, b)
    assert knob_config_signature(cfg, a) != knob_config_signature(cfg, c)
