"""Digital-twin capacity plane (rafiki_tpu/obs/twin/, docs/twin.md).

What is being verified, layer by layer:

* determinism — one seed reproduces a simulation's event log and
  every headline metric bit-for-bit; different seeds diverge;
* queueing physics — at low utilization with exponential service the
  engine reproduces the M/M/1 closed-form mean sojourn;
* drift-proofing — the twin's admission/quorum/breaker constants ARE
  the live gateway/predictor objects (import identity), shed fires at
  exactly the live max_queue bound, breakers trip at exactly
  breaker_failures;
* calibration — missing journal kinds fail loudly listing every one;
  the scaled() mis-calibration knob rejects unknown segments;
* validation — the predicted-vs-measured gate passes a faithful
  calibration and fails a deliberately halved forward time;
* planning — replayed arrivals preserve per-bucket counts, the sweep
  is deterministic, the fleet search finds the smallest compliant
  worker count, and the chaos pre-gate forecasts only serving specs.
"""

from __future__ import annotations

import dataclasses
import json
import random

import pytest

from rafiki_tpu.gateway.gateway import (DEADLINE_RESERVE_FRAC,
                                        GatewayConfig, LATENCY_EWMA_ALPHA)
from rafiki_tpu.obs.twin import load as load_mod
from rafiki_tpu.obs.twin import pregate, whatif
from rafiki_tpu.obs.twin.calibration import (Calibration, CalibrationError,
                                             SAMPLED_SEGMENTS)
from rafiki_tpu.obs.twin.engine import (TwinConfig, result_fingerprint,
                                        simulate)


def _open_cal(forward, workers=1, **segments):
    """A calibration with wide-open gateway knobs so only the segment
    physics under test shape the result."""
    return Calibration(
        segments=dict({"forward": sorted(forward)}, **segments),
        gateway={"max_inflight": 10 ** 6, "max_queue": 10 ** 6,
                 "default_deadline_s": 10 ** 6, "min_replies": None,
                 "hedge_grace_s": 0.0, "policy": "replicate-all",
                 "breaker_failures": 3, "breaker_cooldown_s": 5.0},
        workers=workers)


# -- determinism -----------------------------------------------------------


def test_same_seed_bit_identical():
    cal = Calibration.nominal(forward_ms=5.0, workers=2)
    cfg = TwinConfig.from_calibration(cal)
    arr = load_mod.synthesize("spike", qps=50, duration_s=4, seed=11)
    a = simulate(cal, cfg, arr, seed=3, record_events=True)
    b = simulate(cal, cfg, arr, seed=3, record_events=True)
    assert a["events"] == b["events"]
    assert result_fingerprint(a) == result_fingerprint(b)


def test_different_seed_diverges():
    cal = Calibration.nominal(forward_ms=5.0, workers=2)
    cfg = TwinConfig.from_calibration(cal)
    arr = load_mod.synthesize("constant", qps=50, duration_s=4, seed=11)
    a = simulate(cal, cfg, arr, seed=3)
    b = simulate(cal, cfg, arr, seed=4)
    assert a["event_log_sha1"] != b["event_log_sha1"]


def test_chaos_same_seed_deterministic():
    cal = Calibration.nominal(forward_ms=5.0, workers=2)
    cfg = TwinConfig.from_calibration(cal)
    arr = load_mod.synthesize("constant", qps=40, duration_s=3, seed=1)
    spec = "seed=5;inference.forward:delay:p=0.3:delay=0.05"
    a = simulate(cal, cfg, arr, seed=9, chaos_spec=spec)
    b = simulate(cal, cfg, arr, seed=9, chaos_spec=spec)
    assert result_fingerprint(a) == result_fingerprint(b)
    assert a["chaos_fired"] > 0
    assert a["p99_ms"] > simulate(cal, cfg, arr, seed=9)["p99_ms"]


def test_load_shapes_deterministic_and_sorted():
    for shape in load_mod.SHAPES:
        a = load_mod.synthesize(shape, qps=30, duration_s=5, seed=2)
        b = load_mod.synthesize(shape, qps=30, duration_s=5, seed=2)
        assert a == b and a == sorted(a) and len(a) > 0
    with pytest.raises(ValueError):
        load_mod.synthesize("sawtooth", qps=30, duration_s=5)


# -- queueing physics ------------------------------------------------------


def test_mm1_mean_sojourn_matches_closed_form():
    """Single worker, batch size 1, exponential service, Poisson
    arrivals at rho=0.2: mean sojourn must be ~1/(mu - lambda)."""
    mu, rho = 100.0, 0.2
    lam = rho * mu
    rng = random.Random(5)
    service = [rng.expovariate(mu) for _ in range(4000)]
    cal = _open_cal(service)
    cfg = TwinConfig.from_calibration(cal, workers=1, worker_batch=1)
    arr, t = [], 0.0
    arng = random.Random(6)
    while len(arr) < 2400:
        t += arng.expovariate(lam)
        arr.append(t)
    res = simulate(cal, cfg, arr, seed=1)
    assert res["shed"] == 0 and res["errors"] == 0
    expected_ms = 1000.0 / (mu - lam)
    assert res["mean_ms"] == pytest.approx(expected_ms, rel=0.15)


def test_worker_microbatching_coalesces():
    """Simultaneous queries must share one forward (pop_queries
    drains the queue), so 16 same-instant requests on one worker take
    ~2 service times (one in-flight batch + one drained batch), not
    16."""
    cal = _open_cal([0.010])
    cfg = TwinConfig.from_calibration(cal, workers=1)
    res = simulate(cal, cfg, [0.0] * 16, seed=0)
    assert res["ok"] == 16
    assert res["p99_ms"] < 3 * 10.0


def test_gateway_batch_former_coalesces_and_reports():
    """With the gateway batch former on (max_batch > 1), same-instant
    requests ride ONE fan-out: the result grows the microbatch block,
    flush count stays below request count, and the former is inside
    the bit-deterministic replay surface. max_batch=1 (batching off)
    must not grow the block at all."""
    cal = _open_cal([0.010])
    cfg = TwinConfig.from_calibration(cal, workers=1, max_batch=8,
                                      max_batch_wait_s=0.002)
    res = simulate(cal, cfg, [0.0] * 16, seed=0)
    assert res["ok"] == 16
    mb = res["microbatch"]
    assert sum(mb["flushes"].values()) < 16
    assert mb["mean_size"] > 1.0
    assert set(mb["flushes"]) <= {"size", "deadline", "drain"}
    assert result_fingerprint(res) == result_fingerprint(
        simulate(cal, cfg, [0.0] * 16, seed=0))
    off = simulate(cal, TwinConfig.from_calibration(cal, workers=1),
                   [0.0] * 16, seed=0)
    assert "microbatch" not in off


# -- drift-proofing against the live serving constants ---------------------


def test_twin_constants_are_live_imports():
    import rafiki_tpu.obs.twin.engine as eng
    from rafiki_tpu.gateway import breaker as live_breaker
    from rafiki_tpu.predictor import predictor as live_predictor
    assert eng.default_quorum is live_predictor.default_quorum
    assert eng.CircuitBreaker is live_breaker.CircuitBreaker
    assert eng.DEADLINE_RESERVE_FRAC is DEADLINE_RESERVE_FRAC
    assert eng.LATENCY_EWMA_ALPHA is LATENCY_EWMA_ALPHA


def test_twinconfig_mirrors_gatewayconfig_defaults():
    g = GatewayConfig()
    t = TwinConfig.from_gateway(g, workers=2)
    assert t.max_inflight == g.max_inflight
    assert t.max_queue == g.max_queue
    assert t.min_replies == g.min_replies
    assert t.hedge_grace_s == g.hedge_grace_s
    assert t.policy == g.policy
    assert t.breaker_failures == g.breaker_failures
    assert t.breaker_cooldown_s == g.breaker_cooldown_s


def test_shed_at_exactly_max_queue():
    """One slot in flight, max_queue waiters: the (2 + max_queue)-th
    simultaneous request is the first to shed, with the live reason."""
    cal = _open_cal([1.0])
    cfg = TwinConfig.from_calibration(cal, workers=1, max_inflight=1,
                                      max_queue=4, deadline_s=10 ** 6,
                                      worker_batch=1)
    res = simulate(cal, cfg, [0.0] * 10, seed=0)
    assert res["shed_reasons"] == {"queue_full": 10 - 1 - 4}
    assert res["shed_rate"] == pytest.approx(5 / 10)


def test_breaker_opens_at_exactly_failure_threshold():
    """Kill one of two workers; every later request counts one failed
    fan-out for it. The open transition must land after exactly
    breaker_failures failures — and never with a huge threshold."""
    cal = _open_cal([0.010], workers=2)
    spec = "seed=1;inference.forward:kill:times=1"
    arr = [i * 0.05 for i in range(30)]
    for threshold in (2, 4):
        cfg = TwinConfig.from_calibration(cal, workers=2,
                                          breaker_failures=threshold)
        res = simulate(cal, cfg, arr, seed=0, chaos_spec=spec)
        opens = [t for t in res["breaker_transitions"] if t[3] == "open"]
        assert res["workers_dead"] and opens, (threshold, res)
        first_open = opens[0][0]
        failures_before = sum(
            1 for e in simulate(cal, cfg, arr, seed=0, chaos_spec=spec,
                                record_events=True)["events"]
            if e[1] == "done" and e[0] <= first_open)
        assert failures_before >= threshold
    cfg = TwinConfig.from_calibration(cal, workers=2, breaker_failures=99)
    res = simulate(cal, cfg, arr, seed=0, chaos_spec=spec)
    assert not res["breaker_transitions"]


# -- calibration -----------------------------------------------------------


def test_calibration_missing_kinds_listed():
    with pytest.raises(CalibrationError) as ei:
        Calibration.from_records([], source="empty")
    assert set(ei.value.missing) == {"serving/hops", "gateway/config"}
    msg = str(ei.value)
    assert "serving/hops" in msg and "gateway/config" in msg


def test_calibration_roundtrip_and_scale():
    cal = Calibration.nominal(forward_ms=4.0, workers=3)
    clone = Calibration.from_dict(
        json.loads(json.dumps(cal.to_dict())))
    assert clone.segments.keys() == cal.segments.keys()
    assert clone.workers == 3
    half = cal.scaled({"forward": 0.5})
    assert max(half.segments["forward"]) == pytest.approx(
        max(cal.segments["forward"]) * 0.5)
    with pytest.raises(ValueError):
        cal.scaled({"admission_wait": 0.5})   # emergent: not scalable
    assert "admission_wait" not in SAMPLED_SEGMENTS


def test_calibration_version_gate():
    d = Calibration.nominal().to_dict()
    d["calibration_version"] = 999
    with pytest.raises(ValueError):
        Calibration.from_dict(d)


# -- validation ------------------------------------------------------------


def _fake_capture(tmp_path, n=60, gap_s=0.05, forward_s=0.020):
    """Journal files for a synthetic captured run: hop chains (for
    calibration), the gateway/config knobs, and serving/request rows
    whose e2e is forward + small wiring overhead."""
    overhead = 0.002
    recs = []
    recs.append({"kind": "gateway", "name": "config", "ts": 0.0, "pid": 1,
                 "max_inflight": 8, "max_queue": 32,
                 "default_deadline_s": 2.0, "min_replies": None,
                 "hedge_grace_s": 0.0, "policy": "replicate-all",
                 "breaker_failures": 3, "breaker_cooldown_s": 5.0})
    for i in range(n):
        t0 = 100.0 + i * gap_s
        marks = [["admit", t0, 1], ["queue", t0 + 1e-4, 1],
                 ["enq", t0 + 2e-4, 1], ["deq", t0 + 3e-4, 2],
                 ["fwds", t0 + 4e-4, 2],
                 ["fwd", t0 + 4e-4 + forward_s, 2],
                 ["reply", t0 + 5e-4 + forward_s, 2],
                 ["dec", t0 + 6e-4 + forward_s, 1]]
        recs.append({"kind": "serving", "name": "hops", "ts": t0, "pid": 1,
                     "chains": {"w0": marks}})
        recs.append({"kind": "serving", "name": "request", "ts": t0,
                     "pid": 1, "queries": 1, "ok": True, "hedged": 0,
                     "timeouts": 0,
                     "e2e_s": round(forward_s + overhead, 6)})
    path = tmp_path / "journal-gateway-1.jsonl"
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return tmp_path


def test_validate_passes_faithful_and_fails_halved(tmp_path):
    from rafiki_tpu.obs.twin import validate as validate_mod
    log_dir = _fake_capture(tmp_path)
    good = validate_mod.validate(log_dir, seed=0)
    assert good["ok"] is True
    assert good["p50_err"] <= good["tolerance"]
    assert good["measured"]["requests"] == 60
    bad = validate_mod.validate(log_dir, seed=0,
                                scales={"forward": 0.5})
    assert bad["ok"] is False
    assert bad["p50_err"] > bad["tolerance"]


def test_validate_needs_enough_requests(tmp_path):
    from rafiki_tpu.obs.twin import validate as validate_mod
    log_dir = _fake_capture(tmp_path, n=5)
    with pytest.raises(ValueError, match="serving/request"):
        validate_mod.validate(log_dir, seed=0)


# -- planning: replay, sweep, fleet, pre-gate ------------------------------


def test_replay_preserves_bucket_counts():
    rows = [{"bucket": 40, "span_s": 1.0, "requests": 3},
            {"bucket": 42, "span_s": 1.0, "requests": 2}]
    arr = load_mod.replay_from_ts(rows, seed=0)
    assert len(arr) == 5 and arr == sorted(arr)
    assert sum(1 for t in arr if t < 1.0) == 3
    assert sum(1 for t in arr if 2.0 <= t < 3.0) == 2
    assert load_mod.replay_from_ts(rows, seed=0) == arr


def test_sweep_deterministic_rows_and_grid_guard():
    cal = Calibration.nominal(forward_ms=5.0, workers=2)
    base = TwinConfig.from_calibration(cal)
    arr = load_mod.synthesize("constant", qps=40, duration_s=3, seed=0)
    grid = {"workers": [1, 2], "queries_per_request": [1, 4]}
    a = whatif.sweep(cal, base, arr, grid, seed=5)
    b = whatif.sweep(cal, base, arr, grid, seed=5)
    assert a == b and len(a) == 4
    assert all(r["first_saturating"] for r in a)
    with pytest.raises(ValueError):
        whatif.sweep(cal, base, arr, {"flux_capacitor": [1]}, seed=5)


def test_fleet_search_smallest_compliant(monkeypatch):
    monkeypatch.delenv("RAFIKI_SLO", raising=False)
    cal = _open_cal([0.05])
    base = TwinConfig.from_calibration(
        cal, policy="least-loaded", worker_batch=1, max_inflight=64,
        max_queue=16, deadline_s=2.0)
    # Long enough that an under-provisioned fleet's backlog actually
    # breaches the 2s deadline — over a short horizon a 1.5x-overloaded
    # pair of workers can ride out the whole run inside the budget.
    arr = load_mod.synthesize("constant", qps=60, duration_s=12, seed=2)
    out = whatif.fleet_search(cal, base, arr, seed=0)
    assert out["satisfied"] is True
    assert out["targets"] == {"p99_ms": 2000.0, "shed_rate": 0.05}
    # 50ms serial service at 60 qps needs >= 3 workers for stability.
    assert out["workers"] >= 3
    assert len(out["scanned"]) == out["workers"]
    again = whatif.fleet_search(cal, base, arr, seed=0)
    assert again == out


def test_pregate_serving_specs_only_and_deterministic():
    delay = "seed=1;inference.forward:delay:p=1.0:delay=0.05"
    a = pregate.forecast(delay, seed=3)
    b = pregate.forecast(delay, seed=3)
    assert a == b
    assert a["delta_p99_ms"] > 0
    assert pregate.forecast("seed=1;checkpoint.save:error:p=1.0") is None


def test_pregate_fleet_covers_match_filtered_worker_ids():
    # A spec pinned to the third replica (w2) must fire against the
    # forecast fleet even though the nominal calibration has 2 workers —
    # otherwise the forecast silently simulates the fault never landing.
    spec = "seed=7;inference.forward:delay:delay=3:match=w2"
    assert pregate._min_fleet_for(spec) == 3
    f = pregate.forecast(spec, seed=0)
    assert f["chaos_fired"] > 0
    assert f["delta_p99_ms"] > 0


def test_scenario_report_carries_forecast_field():
    from rafiki_tpu.chaos.runner import ScenarioReport
    rep = ScenarioReport(name="x", passed=True, checks=[], schedule=[],
                         duration_s=0.1, twin_forecast={"spec": "s"})
    assert rep.to_dict()["twin_forecast"] == {"spec": "s"}


def test_queries_per_request_rides_arrival_tuples():
    cal = Calibration.nominal(forward_ms=2.0, workers=2)
    cfg = TwinConfig.from_calibration(cal)
    res = simulate(cal, cfg, [(0.0, 3), (0.1, 1)], seed=0)
    assert res["requests"] == 2 and res["ok"] == 2
