"""Device-resident dataset fast path: scan epochs, exact eval parity.

The single-device trial loop runs each epoch as ONE lax.scan over a
device-resident dataset copy (host ships only the shuffle permutation).
These tests pin: exact evaluation parity with the per-batch path, that
training through the fast path actually learns, the HBM cap fallback,
and that the device copy is cached on the dataset object (one upload
per dataset per device, shared across trials).
"""

import numpy as np
import pytest

import jax.numpy as jnp
import optax

from rafiki_tpu.model.dataset import dataset_utils, synthetic_images
from rafiki_tpu.ops.train import TrainLoop, cross_entropy_loss, get_device_dataset

TRAIN = "synthetic://images?classes=4&n=300&w=8&h=8&c=1&seed=0"
VAL = "synthetic://images?classes=4&n=150&w=8&h=8&c=1&seed=1"


def _loop(seed=0):
    def init_fn(key):
        import jax

        k1, _ = jax.random.split(key)
        return {"w": jax.random.normal(k1, (64, 4)) * 0.05, "b": jnp.zeros((4,))}

    def apply_fn(params, b):
        x = b["x"].reshape((b["x"].shape[0], -1))
        return x @ params["w"] + params["b"]

    def loss_fn(params, b, rng, hyper):
        loss, acc = cross_entropy_loss(apply_fn(params, b), b["y"])
        return loss, {"acc": acc}

    return TrainLoop(init_fn, apply_fn, loss_fn, seed=seed,
                     hyper={"lr": 5e-2, "warmup": 1.0})


def test_fast_eval_exactly_matches_slow(monkeypatch):
    ds = dataset_utils.load(VAL)
    loop = _loop()
    fast = loop.evaluate(ds, batch_size=64)  # 2 full scans + remainder 22
    monkeypatch.setenv("RAFIKI_DEVICE_DATASET_MAX_MB", "0")  # force slow path
    slow = loop.evaluate(ds, batch_size=64)
    assert fast == slow  # integer-count sums: exact, order-independent


def test_fast_epoch_learns():
    tr = dataset_utils.load(TRAIN)
    va = dataset_utils.load(VAL)
    loop = _loop()
    before = loop.evaluate(va, batch_size=64)
    for epoch in range(8):
        metrics = loop.run_epoch(tr, batch_size=64, epoch_seed=epoch)
        assert np.isfinite(metrics["loss"])
    after = loop.evaluate(va, batch_size=64)
    assert after > max(before, 0.5)


def test_fast_and_slow_epochs_train_identically(monkeypatch):
    """Both run_epoch branches draw the SAME shuffle permutation and
    the same per-step rng splits, so fast and slow paths must produce
    matching params and final-step metrics (up to compile-dependent
    float reassociation)."""
    tr = dataset_utils.load(TRAIN)
    fast_loop = _loop(seed=3)
    mf = fast_loop.run_epoch(tr, batch_size=64, epoch_seed=0)

    monkeypatch.setenv("RAFIKI_DEVICE_DATASET_MAX_MB", "0")  # force slow path
    slow_loop = _loop(seed=3)
    ms = slow_loop.run_epoch(tr, batch_size=64, epoch_seed=0)

    np.testing.assert_allclose(mf["loss"], ms["loss"], rtol=1e-4)
    np.testing.assert_allclose(np.asarray(fast_loop.params["w"]),
                               np.asarray(slow_loop.params["w"]),
                               rtol=1e-4, atol=1e-6)


def test_device_copy_cached_on_dataset():
    ds = synthetic_images(classes=3, n=64, w=4, h=4, c=1, seed=0)
    x1, y1 = get_device_dataset(ds)
    x2, y2 = get_device_dataset(ds)
    assert x1 is x2 and y1 is y2  # one upload per dataset per device
    np.testing.assert_array_equal(np.asarray(y1), ds.y)


def test_masked_dataset_uses_slow_path():
    """Corpus datasets (mask present) must keep the per-batch path —
    the scan fast path only models plain x/y batches."""
    from rafiki_tpu.model.dataset import synthetic_corpus

    ds = synthetic_corpus(vocab=20, tags=4, n=48, length=6, seed=0)
    assert ds.mask is not None
    loop = _loop()
    assert not loop._fits_device_fast_path(ds)
