"""JSONL event stream: emission, filtering, torn-line tolerance."""

import json

from rafiki_tpu.utils.events import EventLog


def test_emit_and_read(tmp_path):
    log = EventLog(tmp_path)
    log.emit("trial_started", trial_id="t1", knobs={"lr": 0.1})
    log.emit("trial_completed", trial_id="t1", score=0.9)
    log.emit("trial_started", trial_id="t2")
    events = list(log.read())
    assert [e["event"] for e in events] == [
        "trial_started", "trial_completed", "trial_started"]
    assert all("time" in e and "pid" in e for e in events)
    completed = list(log.read("trial_completed"))
    assert len(completed) == 1 and completed[0]["score"] == 0.9


def test_unconfigured_is_noop(tmp_path):
    log = EventLog()
    log.emit("whatever", x=1)  # must not raise
    assert list(log.read()) == []


def test_torn_lines_skipped(tmp_path):
    log = EventLog(tmp_path)
    log.emit("good", n=1)
    with open(log.path, "a") as f:
        f.write('{"event": "torn", "n')  # crashed writer mid-line
    log2 = EventLog()
    log2._path = log.path
    assert [e["event"] for e in log2.read()] == ["good"]


def test_scheduler_emits_lifecycle(tmp_path):
    """The local scheduler + worker emit job and trial events."""
    from rafiki_tpu.scheduler import LocalScheduler
    from rafiki_tpu.store import MetaStore, ParamsStore
    from rafiki_tpu.utils.events import events

    from tests.test_scheduler import FF_SOURCE, TRAIN, VAL

    events.configure(tmp_path)
    try:
        store = MetaStore(tmp_path / "meta.sqlite3")
        params = ParamsStore(tmp_path / "params")
        model = store.create_model("tinyff", "IMAGE_CLASSIFICATION", None,
                                   FF_SOURCE, "TinyFF")
        job = store.create_train_job("evapp", "IMAGE_CLASSIFICATION", None,
                                     TRAIN, VAL, {"MODEL_TRIAL_COUNT": 2})
        store.create_sub_train_job(job["id"], model["id"])
        LocalScheduler(store, params).run_train_job(job["id"], n_workers=1,
                                                    advisor_kind="random")
        kinds = [e["event"] for e in events.read()]
        assert kinds[0] == "train_job_started"
        assert kinds.count("trial_started") == 2
        assert kinds.count("trial_completed") == 2
        assert kinds[-1] == "train_job_finished"
        finished = list(events.read("train_job_finished"))[-1]
        assert finished["status"] == "COMPLETED"
    finally:
        events.close()
        events._path = None
