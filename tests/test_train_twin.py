"""Train twin (docs/twin.md): analytic exactness, bit-identical
replay, validate polarities on synthetic journals, calibration
fail-loud, pregate forecasts, and the advisory placement hook."""

from __future__ import annotations

import json

import pytest

from rafiki_tpu.obs.journal import journal, read_dir
from rafiki_tpu.obs.twin.calibration import CalibrationError
from rafiki_tpu.obs.twin.train.calibration import (TrainCalibration,
                                                   TrainCalibrationError)
from rafiki_tpu.obs.twin.train.engine import (TrainTwinConfig, _assign,
                                              result_fingerprint, simulate)
from rafiki_tpu.obs.twin.train import pregate, validate as validate_mod


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

def _analytic_cal() -> TrainCalibration:
    """Hand-computable bundle: every (packing_key, width) the sweep
    touches has exactly ONE sample, so the simulation is arithmetic.

    pkA: width-2 packs, cold 2.0, warm 1.0, 3 epochs.
    pkB: width-1 packs, cold 3.0, warm 0.5, 2 epochs.
    """
    return TrainCalibration(
        steps={"pkA": {"2": [1.0]}, "pkB": {"1": [0.5]}},
        compiles={"pkA": {"2": [2.0]}, "pkB": {"1": [3.0]}},
        packs=[{"packing_key": "pkA", "k": 2, "epochs": 3},
               {"packing_key": "pkB", "k": 1, "epochs": 2}],
        sweep={"chips": 2, "trials_per_chip": 2, "n_trials": 6},
        cost={}, epoch_overhead_s=0.0, source="analytic")


def _analytic_trials():
    return ([{"id": f"a{i}", "packing_key": "pkA", "epochs": 3}
             for i in range(4)]
            + [{"id": f"b{i}", "packing_key": "pkB", "epochs": 2}
               for i in range(2)])


def _spread_cal() -> TrainCalibration:
    """Multi-sample distributions so different seeds draw differently."""
    return TrainCalibration(
        steps={"pkA": {"2": [round(0.5 + 0.1 * i, 3) for i in range(16)]}},
        compiles={"pkA": {"2": [4.0, 2.5]}},
        packs=[{"packing_key": "pkA", "k": 2, "epochs": 6}],
        sweep={"chips": 2, "trials_per_chip": 2, "n_trials": 8},
        cost={}, epoch_overhead_s=0.0, source="spread")


def _write_synthetic_journal(log_dir, step_scale: float = 1.0) -> None:
    """A captured 2-chip sweep as literal journal lines: per chip one
    pack (pk, width 2, 3 epochs) whose epochs are cold 2s + warm 1s +
    warm 1s back to back — measured wall exactly 4.0s, fitted
    epoch_overhead exactly 0."""
    rows = [
        {"ts": 1000.0, "kind": "mesh", "name": "sweep_started",
         "job_id": "j1", "chips": 2, "trials_per_chip": 2, "n_trials": 4},
    ]
    for chip in range(2):
        rows.append({"ts": 1000.5, "kind": "mesh", "name": "pack_formed",
                     "job_id": "j1", "chip": chip, "packing_key": "pk",
                     "k": 2, "fill_ratio": 1.0, "epochs": 3,
                     "trial_ids": [f"t{chip}a", f"t{chip}b"]})
        for ts, dt, cold in ((1002.0, 2.0 * step_scale, True),
                             (1003.0, 1.0 * step_scale, False),
                             (1004.0, 1.0 * step_scale, False)):
            rows.append({"ts": ts, "kind": "perf", "name": "step",
                         "key_hash": "kh", "dt": dt, "cold": cold,
                         "program_kind": "packed", "k": 2,
                         "packing_key": "pk"})
    with open(log_dir / "journal-test-1.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


# ---------------------------------------------------------------------------
# engine: analytic exactness + assignment mirror
# ---------------------------------------------------------------------------

def test_assignment_mirrors_mesh_round_robin():
    packs = _assign(_analytic_trials(), chips=2, k=2)
    # Bucket pkA first (first appearance), global cursor round-robins
    # its 4 rows a0..a3 across chips, then pkB's 2 rows continue.
    assert [(p["chip"], p["packing_key"], p["members"]) for p in packs] == [
        (0, "pkA", ["a0", "a2"]), (0, "pkB", ["b0"]),
        (1, "pkA", ["a1", "a3"]), (1, "pkB", ["b1"])]


def test_analytic_makespan_exact():
    cfg = TrainTwinConfig(chips=2, k=2, n_trials=6)
    res = simulate(_analytic_cal(), cfg, trials=_analytic_trials(), seed=0)
    # Per chip: pkA pack = 2.0 cold + 1.0 + 1.0 warm = 4.0s, then the
    # queued pkB pack = 3.0 cold + 0.5 warm = 3.5s -> 7.5s total, both
    # chips symmetric.
    assert res["status"] == "ok"
    assert res["makespan_s"] == 7.5
    assert res["completed"] == 6
    assert res["trials_per_hour"] == pytest.approx(6 / 7.5 * 3600)
    assert res["compile_s"] == 2 * (2.0 + 3.0)
    assert res["step_s"] == 2 * (1.0 + 1.0 + 0.5)
    assert res["utilization"] == 1.0


def test_cold_order_statistic_first_pack_pays_true_compile():
    # Two width-2 pkA packs on ONE chip: the first pays the slowest
    # cold sample (4.0 = the true compile), the second the 2.5 program
    # cache hit. Warm epochs pin to a single sample for exactness.
    cal = TrainCalibration(
        steps={"pkA": {"2": [1.0]}}, compiles={"pkA": {"2": [4.0, 2.5]}},
        packs=[], sweep={}, cost={}, epoch_overhead_s=0.0, source="t")
    packs = [{"chip": 0, "packing_key": "pkA", "epochs": 2,
              "members": ["x", "y"]},
             {"chip": 0, "packing_key": "pkA", "epochs": 2,
              "members": ["u", "v"]}]
    res = simulate(cal, TrainTwinConfig(chips=1, k=2), packs=packs, seed=0)
    assert res["makespan_s"] == (4.0 + 1.0) + (2.5 + 1.0)


def test_epoch_overhead_rides_every_epoch():
    cal = _analytic_cal()
    cal.epoch_overhead_s = 0.25
    cfg = TrainTwinConfig(chips=2, k=2, n_trials=6)
    res = simulate(cal, cfg, trials=_analytic_trials(), seed=0)
    # 5 epochs per chip (3 pkA + 2 pkB) x 0.25s on top of 7.5s.
    assert res["makespan_s"] == 7.5 + 5 * 0.25


def test_bit_identical_replay():
    cal = _spread_cal()
    cfg = TrainTwinConfig(chips=2, k=2, n_trials=8)
    a = simulate(cal, cfg, seed=7, record_events=True)
    b = simulate(cal, cfg, seed=7, record_events=True)
    assert a == b
    assert result_fingerprint(a) == result_fingerprint(b)
    c = simulate(cal, cfg, seed=8)
    assert c["event_log_sha1"] != a["event_log_sha1"]


def test_eviction_counts_completed_and_narrows_pack():
    cal = _spread_cal()
    cfg = TrainTwinConfig(chips=2, k=2, evict_prob=0.5)
    res = simulate(cal, cfg, seed=3)
    assert res["status"] == "ok"
    # Early-stopped members are verdicts, not losses: everything still
    # completes, and eviction must actually have fired at p=0.5.
    assert res["completed"] == res["trials"] == 4
    assert res["evicted"] > 0
    assert simulate(cal, cfg, seed=3) == res  # evict stream is seeded


def test_chaos_preempt_repacks_onto_survivor():
    cal = _spread_cal()
    cfg = TrainTwinConfig(chips=2, k=2, n_trials=8)
    spec = "scheduler.preempt:preempt:match=chip0:times=1"
    res = simulate(cal, cfg, seed=7, chaos_spec=spec)
    base = simulate(cal, cfg, seed=7)
    assert res["chaos_fired"] == 1
    assert res["chips_lost"] == [0]
    assert res["repacks"] > 0
    assert res["completed"] == res["trials"]  # nothing stranded
    assert res["makespan_s"] > base["makespan_s"]  # the loss cost time


def test_chaos_supervisor_host_loss_aborts():
    cal = _spread_cal()
    cfg = TrainTwinConfig(chips=4, k=2, n_trials=8, chips_per_host=2)
    res = simulate(cal, cfg, seed=0,
                   chaos_spec="host.loss:kill:match=g0h0:times=1")
    assert res["status"] == "supervisor_lost"
    ok = simulate(cal, cfg, seed=0,
                  chaos_spec="host.loss:kill:match=g0h1:times=1")
    assert ok["status"] == "ok"
    assert ok["hosts_lost"] == [1] and ok["chips_lost"] == [2, 3]


# ---------------------------------------------------------------------------
# calibration: fail-loud, scaling, roundtrip
# ---------------------------------------------------------------------------

def test_calibration_empty_dir_lists_both_missing_kinds(tmp_path):
    with pytest.raises(TrainCalibrationError) as ei:
        TrainCalibration.from_journal_dir(tmp_path)
    assert set(ei.value.missing) == {"perf/step", "mesh/pack_formed"}
    assert str(tmp_path) in str(ei.value)
    # Subclasses the serving error so shared handlers catch both.
    assert isinstance(ei.value, CalibrationError)


def test_calibration_partial_capture_names_the_absent_kind(tmp_path):
    with open(tmp_path / "journal-test-1.jsonl", "w") as f:
        f.write(json.dumps({"ts": 1.0, "kind": "perf", "name": "step",
                            "dt": 0.5, "cold": False, "k": 2,
                            "packing_key": "pk"}) + "\n")
    with pytest.raises(TrainCalibrationError) as ei:
        TrainCalibration.from_journal_dir(tmp_path)
    assert ei.value.missing == ["mesh/pack_formed"]


def test_scaled_rejects_unknown_segment():
    with pytest.raises(ValueError, match="step"):
        _analytic_cal().scaled({"forward": 2.0})


def test_calibration_roundtrip_and_version_gate(tmp_path):
    cal = _analytic_cal()
    path = tmp_path / "cal.json"
    cal.save(path)
    loaded = TrainCalibration.load(path)
    assert loaded.steps == cal.steps
    assert loaded.compiles == cal.compiles
    assert loaded.sweep == cal.sweep
    doc = json.loads(path.read_text())
    doc["train_calibration_version"] = 99
    with pytest.raises(ValueError, match="99"):
        TrainCalibration.from_dict(doc)


# ---------------------------------------------------------------------------
# validate: both polarities on synthetic journals
# ---------------------------------------------------------------------------

def test_validate_correct_calibration_passes(tmp_path):
    _write_synthetic_journal(tmp_path)
    doc = validate_mod.validate(tmp_path, seed=0)
    # Measured wall: last epoch end 1004.0 minus first epoch start
    # (1002.0 - 2.0) = 4.0s; replayed packs cost exactly 2+1+1 per
    # chip with zero fitted overhead -> both errors exactly 0.
    assert doc["measured"]["wall_s"] == 4.0
    assert doc["measured"]["trials"] == 4
    assert doc["predicted"]["wall_s"] == 4.0
    assert doc["tph_err"] == 0.0 and doc["wall_err"] == 0.0
    assert doc["ok"] is True
    # Byte-identical replay: the artifact hashes the same event log.
    again = validate_mod.validate(tmp_path, seed=0)
    assert again["event_log_sha1"] == doc["event_log_sha1"]


def test_validate_doctored_2x_step_time_fails(tmp_path):
    _write_synthetic_journal(tmp_path)
    doc = validate_mod.validate(tmp_path, seed=0,
                                scales={"step": 2.0})
    # Warm epochs double (cold unscaled): predicted 2+2+2=6.0 vs
    # measured 4.0 -> 50% wall error, far over the 25% gate.
    assert doc["predicted"]["wall_s"] == 6.0
    assert doc["wall_err"] == 0.5
    assert doc["ok"] is False


def test_validate_empty_dir_raises_calibration_error(tmp_path):
    with pytest.raises(TrainCalibrationError):
        validate_mod.validate(tmp_path, seed=0)


# ---------------------------------------------------------------------------
# pregate: autoscale forecast + veto, chaos forecast
# ---------------------------------------------------------------------------

def test_pregate_forecast_deterministic_and_gain_rides_back():
    cal = _spread_cal()
    a = pregate.forecast(1, 4, calibration=cal, seed=0)
    assert a == pregate.forecast(1, 4, calibration=cal, seed=0)
    assert a["veto"] is False
    assert a["delta_trials_per_hour"] > 0
    assert a["target_forecast"]["makespan_s"] < a["baseline"]["makespan_s"]


def test_pregate_vetoes_pointless_scale_up():
    # One single trial: a second chip cannot speed up one pack, so the
    # predicted gain is 0% < the 2% bar -> veto, with a reason.
    cal = _spread_cal()
    f = pregate.forecast(1, 2, calibration=cal, n_trials=1, seed=0)
    assert f["veto"] is True
    assert "trials/hour" in f["veto_reason"]


def test_pregate_lane_filter():
    cal = _spread_cal()
    fn = pregate.sweep_chip_pregate(calibration=cal)
    assert fn("sweep", 1, 4) is not None
    assert fn("serving", 1, 4) is None
    assert fn("sweep", 2, 2) is None


def test_chaos_forecast_only_on_sweep_sites():
    cal = _spread_cal()
    assert pregate.chaos_forecast("gateway.admit:drop:p=0.5",
                                  calibration=cal) is None
    cf = pregate.chaos_forecast(
        "scheduler.preempt:preempt:match=chip0:times=1",
        calibration=cal, chips=2, seed=0)
    assert cf["chaos_fired"] == 1
    assert cf["delta_makespan_s"] > 0


# ---------------------------------------------------------------------------
# placement hook: advisory consultation, journaled
# ---------------------------------------------------------------------------

def test_placement_consult_journals_recommendation(tmp_path):
    cap = tmp_path / "cap"
    cap.mkdir()
    _write_synthetic_journal(cap)
    out = tmp_path / "out"
    out.mkdir()
    journal.configure(out, role="test")
    try:
        from rafiki_tpu.obs.twin.train import placement
        rec = placement.consult(job_id="j1", chips=2, k=2,
                                budget={"MODEL_TRIAL_COUNT": 4},
                                log_dir=str(cap), seed=0)
    finally:
        journal.close()
    assert rec["best_k"] and rec["best_split"]["chips"] >= 1
    assert rec["calibration_source"] == str(cap)
    recs = [r for r in read_dir(out)
            if r.get("kind") == "twin" and r.get("name") == "placement"]
    assert len(recs) == 1
    assert recs[0]["advisory"] is True
    assert recs[0]["recommendation"]["best_split"] == rec["best_split"]


def test_mesh_sweep_consults_twin_at_admission(tmp_path, monkeypatch):
    """RAFIKI_TWIN_PLACEMENT end to end: a real mini sweep whose log
    dir is pre-populated with a prior capture journals an advisory
    twin/placement record at admission, then runs untouched — and its
    own mesh/pack_formed + packing-key-stamped perf/step records make
    the NEXT calibration (the closed loop the twin rides)."""
    from rafiki_tpu.chaos.scenarios import FF_SOURCE, TRAIN, VAL
    from rafiki_tpu.scheduler import MeshSweepScheduler
    from rafiki_tpu.store import MetaStore, ParamsStore

    _write_synthetic_journal(tmp_path)  # prior capture -> calibration
    monkeypatch.setenv("RAFIKI_LOG_DIR", str(tmp_path))
    monkeypatch.setenv("RAFIKI_TWIN_PLACEMENT", "1")
    journal.configure(tmp_path, role="test")
    try:
        store = MetaStore(tmp_path / "meta.sqlite3")
        params = ParamsStore(tmp_path / "params")
        model = store.create_model("twinff", "IMAGE_CLASSIFICATION", None,
                                   FF_SOURCE, "ChaosFF")
        job = store.create_train_job("twinhook", "IMAGE_CLASSIFICATION",
                                     None, TRAIN, VAL,
                                     {"MODEL_TRIAL_COUNT": 2})
        store.create_sub_train_job(job["id"], model["id"])
        result = MeshSweepScheduler(store, params).run_sweep(
            job["id"], chips=2, trials_per_chip=1, advisor_kind="random")
    finally:
        journal.close()
    assert result.status == "COMPLETED", result.errors
    recs = read_dir(tmp_path)
    placements = [r for r in recs if r.get("kind") == "twin"
                  and r.get("name") == "placement"
                  and r.get("job_id") == job["id"]]
    assert len(placements) == 1
    assert placements[0]["advisory"] is True
    assert placements[0].get("error") is None
    assert placements[0]["recommendation"]["best_split"]
    # Satellite records the twin itself feeds on, from the real sweep:
    formed = [r for r in recs if r.get("kind") == "mesh"
              and r.get("name") == "pack_formed"
              and r.get("job_id") == job["id"]]
    assert formed and all(r["trial_ids"] and r["packing_key"]
                          for r in formed)
    stamped = [r for r in recs if r.get("kind") == "perf"
               and r.get("name") == "step" and r.get("packing_key")
               and r.get("program_kind") == "packed"]
    assert stamped
