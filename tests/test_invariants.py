"""Property-based scheduler invariants under randomized interleavings.

SURVEY.md §5: the reference rests its concurrency correctness on
Postgres transactions; the rebuild's prescription is "property tests on
scheduler invariants instead". These tests hammer the meta store's
claim / heartbeat / recover primitives from many threads with seeded
random interleavings and assert the three invariants that hold the
AutoML loop together:

  1. BUDGET — the number of trials created never exceeds the job's
     trial budget, no matter how many workers race the claim;
  2. EXACTLY-ONCE ADOPTION — concurrent recovery sweeps never
     double-adopt an orphan (atomic CAS on status + observed owner);
  3. NO TERMINAL REGRESSION — a COMPLETED/ERRORED trial never goes
     back to RUNNING (a zombie sweep cannot resurrect a finished
     trial).
"""

import random
import threading

import pytest

from rafiki_tpu.store import MetaStore


@pytest.fixture()
def store(tmp_path):
    return MetaStore(tmp_path / "meta.sqlite3")


def _job(store, budget):
    model = store.create_model("m", "IMAGE_CLASSIFICATION", None, b"x=1", "X")
    job = store.create_train_job("app", "IMAGE_CLASSIFICATION", None,
                                 "t", "v", {"MODEL_TRIAL_COUNT": budget})
    sub = store.create_sub_train_job(job["id"], model["id"])
    return job, sub, model


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_budget_never_exceeded_under_racing_claims(store, seed):
    budget = 23
    job, sub, model = _job(store, budget)
    rng = random.Random(seed)
    n_workers = 8
    barrier = threading.Barrier(n_workers)
    claimed_counts = [0] * n_workers

    def worker(w):
        barrier.wait()  # maximal contention at the first claim
        while store.claim_trial_slot(sub["id"], budget):
            t = store.create_trial(sub["id"], "X", {"k": w}, worker_id=f"w{w}",
                                   service_id=None)
            claimed_counts[w] += 1
            if rng.random() < 0.5:
                store.mark_trial_as_completed(t["id"], rng.random(), None)
            else:
                store.mark_trial_as_errored(t["id"], "boom")

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(n_workers)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()

    trials = store.get_trials_of_sub_train_job(sub["id"])
    assert len(trials) == budget  # never over, and the budget drains fully
    assert sum(claimed_counts) == budget
    # trial numbering stayed dense and unique under contention
    assert sorted(t["no"] for t in trials) == list(range(1, budget + 1))


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_orphan_adoption_is_exactly_once(store, seed):
    """k sweeper threads race over the same orphan set; the CAS must
    hand each orphan to exactly one sweeper."""
    budget = 40
    job, sub, model = _job(store, budget)
    rng = random.Random(seed)
    orphan_ids = []
    for i in range(budget):
        svc = store.create_service("TRAIN_WORKER")
        # dead service -> its RUNNING trial is an orphan
        store.update_service(svc["id"], status="ERRORED")
        t = store.create_trial(sub["id"], "X", {"i": i}, worker_id=f"dead{i}",
                               service_id=svc["id"])
        orphan_ids.append(t["id"])

    orphans = store.get_orphaned_trials(stale_after_s=0.0)
    assert {t["id"] for t in orphans} == set(orphan_ids)

    n_sweepers = 6
    adopted = [[] for _ in range(n_sweepers)]
    barrier = threading.Barrier(n_sweepers)

    def sweeper(s):
        my_orphans = list(orphans)
        rng_local = random.Random(seed * 100 + s)
        rng_local.shuffle(my_orphans)
        barrier.wait()
        for t in my_orphans:
            svc = store.create_service("TRAIN_WORKER")
            if store.adopt_trial(t["id"], t["service_id"], svc["id"], f"rec-s{s}"):
                adopted[s].append(t["id"])

    threads = [threading.Thread(target=sweeper, args=(s,)) for s in range(n_sweepers)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()

    all_adopted = [tid for lst in adopted for tid in lst]
    assert len(all_adopted) == len(set(all_adopted)), "an orphan was double-adopted"
    assert set(all_adopted) == set(orphan_ids)  # none lost, none duplicated


def test_terminal_status_never_regresses(store):
    """A zombie sweep holding a stale orphan observation cannot flip a
    since-finished trial back to RUNNING."""
    budget = 10
    job, sub, model = _job(store, budget)
    svc = store.create_service("TRAIN_WORKER")
    store.update_service(svc["id"], status="ERRORED")
    t = store.create_trial(sub["id"], "X", {}, worker_id="w0",
                           service_id=svc["id"])
    # sweep observes the orphan...
    orphans = store.get_orphaned_trials(stale_after_s=0.0)
    assert [o["id"] for o in orphans] == [t["id"]]
    # ...but the original worker was merely slow, not dead: it finishes
    store.mark_trial_as_completed(t["id"], 0.91, None)
    # the stale sweep's adoption must now fail
    rec = store.create_service("TRAIN_WORKER")
    assert not store.adopt_trial(t["id"], svc["id"], rec["id"], "rec")
    assert store.get_trial(t["id"])["status"] == "COMPLETED"
    assert store.get_trial(t["id"])["score"] == 0.91


@pytest.mark.parametrize("seed", [0, 1])
def test_randomized_lifecycle_interleaving(store, seed):
    """Free-for-all: workers claim/complete/die, sweepers adopt and
    finish, heartbeats interleave. Afterwards every invariant holds and
    every trial is terminal."""
    budget = 30
    job, sub, model = _job(store, budget)
    stop = threading.Event()
    status_log = {}  # trial_id -> list of observed statuses
    log_lock = threading.Lock()

    def worker(w):
        rng = random.Random(seed * 31 + w)
        while store.claim_trial_slot(sub["id"], budget):
            svc = store.create_service("TRAIN_WORKER")
            t = store.create_trial(sub["id"], "X", {"w": w}, worker_id=f"w{w}",
                                   service_id=svc["id"])
            for _ in range(rng.randrange(3)):
                store.update_service(svc["id"], heartbeat=True)
            r = rng.random()
            if r < 0.45:
                store.mark_trial_as_completed(t["id"], rng.random(), None)
                store.update_service(svc["id"], status="STOPPED")
            elif r < 0.7:
                store.mark_trial_as_errored(t["id"], "boom")
                store.update_service(svc["id"], status="STOPPED")
            else:  # die mid-trial: leave RUNNING with a dead service
                store.update_service(svc["id"], status="ERRORED")

    def sweeper(s):
        rng = random.Random(seed * 97 + s)
        while not stop.is_set():
            for t in store.get_orphaned_trials(stale_after_s=0.0):
                svc = store.create_service("TRAIN_WORKER")
                if store.adopt_trial(t["id"], t["service_id"], svc["id"], f"rec{s}"):
                    # "re-run" then finish
                    store.mark_trial_as_completed(t["id"], rng.random(), None)
                    store.update_service(svc["id"], status="STOPPED")

    def monitor():
        while not stop.is_set():
            for t in store.get_trials_of_sub_train_job(sub["id"]):
                with log_lock:
                    hist = status_log.setdefault(t["id"], [])
                    if not hist or hist[-1] != t["status"]:
                        hist.append(t["status"])

    workers = [threading.Thread(target=worker, args=(w,)) for w in range(6)]
    sweepers = [threading.Thread(target=sweeper, args=(s,)) for s in range(2)]
    mon = threading.Thread(target=monitor)
    for th in workers + sweepers + [mon]:
        th.start()
    for th in workers:
        th.join()
    # let sweepers drain remaining orphans
    deadline = threading.Event()
    for _ in range(200):
        if not store.get_orphaned_trials(stale_after_s=0.0):
            break
        deadline.wait(0.05)
    stop.set()
    for th in sweepers + [mon]:
        th.join()

    trials = store.get_trials_of_sub_train_job(sub["id"])
    assert len(trials) == budget
    assert all(t["status"] in ("COMPLETED", "ERRORED") for t in trials)
    # no observed terminal -> non-terminal transition
    terminal = {"COMPLETED", "ERRORED"}
    for tid, hist in status_log.items():
        for a, b in zip(hist, hist[1:]):
            assert not (a in terminal and b == "RUNNING"), (tid, hist)
