import numpy as np

from rafiki_tpu.advisor import AdvisorService, GpAdvisor, RandomAdvisor, make_advisor
from rafiki_tpu.model.knobs import CategoricalKnob, FixedKnob, FloatKnob, IntegerKnob


def _config():
    return {
        "x": FloatKnob(-2.0, 2.0),
        "y": FloatKnob(1e-3, 1e1, is_exp=True),
        "n": IntegerKnob(1, 8),
        "c": CategoricalKnob(["a", "b"]),
        "fixed": FixedKnob(42),
    }


def _objective(knobs):
    # peak at x=0.5, y=1.0, n=4, c='b'
    return (
        -((knobs["x"] - 0.5) ** 2)
        - (np.log10(knobs["y"]) ** 2)
        - 0.05 * (knobs["n"] - 4) ** 2
        + (0.5 if knobs["c"] == "b" else 0.0)
    )


def test_random_advisor_proposals_valid():
    adv = RandomAdvisor(_config(), seed=0)
    from rafiki_tpu.model.knobs import validate_knobs

    for _ in range(50):
        knobs = adv.propose()
        validate_knobs(_config(), knobs)
        assert knobs["fixed"] == 42


def _hard_config():
    return {
        "x": FloatKnob(-2.0, 2.0),
        "y": FloatKnob(1e-3, 1e1, is_exp=True),
        "z": FloatKnob(0.0, 1.0),
        "n": IntegerKnob(1, 8),
        "c": CategoricalKnob(["a", "b"]),
        "fixed": FixedKnob(42),
    }


def _hard_objective(k):
    # Narrow smooth peak (x=0.5, y=1.0, z=0.3, n=4, c='b'), max 0.5:
    # narrow enough that 40 random draws rarely land near it, smooth
    # enough that a working GP reliably climbs to it.
    return (
        -3.0 * (k["x"] - 0.5) ** 2
        - 1.5 * np.log10(k["y"]) ** 2
        - 4.0 * (k["z"] - 0.3) ** 2
        - 0.08 * (k["n"] - 4) ** 2
        + (0.5 if k["c"] == "b" else 0.0)
    )


def test_gp_advisor_beats_random():
    """GP must find a STRICTLY better optimum than random search with
    the same budget — by a margin, so this fails if the GP is swapped
    for (or degrades to) random sampling. Calibrated over 6 seeds:
    GP mean ~0.49 (worst seed 0.48), random mean ~-0.28 (best seed
    0.32); the 0.3 margin sits well inside the gap."""
    budget = 40
    results = {}
    for kind in ("gp", "random"):
        bests = []
        for seed in range(6):
            adv = make_advisor(_hard_config(), kind=kind, seed=seed)
            for _ in range(budget):
                knobs = adv.propose()
                adv.feedback(_hard_objective(knobs), knobs)
            bests.append(adv.best()[1])
        results[kind] = float(np.mean(bests))
    assert results["gp"] >= results["random"] + 0.3, results
    # and the GP actually solves the problem, not merely beats random
    assert results["gp"] >= 0.4, results


def test_gp_pending_points_drain():
    adv = GpAdvisor(_config(), seed=0, n_initial=4)
    for _ in range(12):
        knobs = adv.propose()
        adv.feedback(_objective(knobs), knobs)
    assert len(adv._pending) == 0  # every proposal scored → removed


def test_gp_concurrent_proposals_differ():
    adv = GpAdvisor(_config(), seed=0, n_initial=4)
    for _ in range(8):
        knobs = adv.propose()
        adv.feedback(_objective(knobs), knobs)
    a = adv.propose()
    b = adv.propose()  # liar penalty should push b away from a
    assert a != b


def test_advisor_service_registry():
    svc = AdvisorService()
    aid = svc.create_advisor(_config(), kind="random", seed=1)
    knobs = svc.propose(aid)
    svc.feedback(aid, 0.5, knobs)
    assert svc.best(aid)[1] == 0.5
    svc.delete_advisor(aid)
    try:
        svc.propose(aid)
        assert False
    except KeyError:
        pass


def test_fixed_only_space():
    adv = make_advisor({"k": FixedKnob(1)}, kind="gp")
    assert adv.propose() == {"k": 1}


def test_tpe_advisor_proposals_valid():
    from rafiki_tpu.advisor import TpeAdvisor
    from rafiki_tpu.model.knobs import validate_knobs

    adv = TpeAdvisor(_config(), seed=0, n_initial=4)
    for i in range(30):
        knobs = adv.propose()
        validate_knobs(_config(), knobs)
        assert knobs["fixed"] == 42
        adv.feedback(_objective(knobs), knobs)
    assert len(adv._pending) == 0


def test_tpe_advisor_beats_random():
    """TPE must also strictly beat random with the same budget — it is
    the second real engine, not a random fallback. Calibrated over 8
    seeds at budget 80: TPE mean ~0.05, random mean ~-0.27; the 0.15
    margin sits inside the gap with room for seed noise."""
    from rafiki_tpu.advisor import TpeAdvisor

    budget = 80
    results = {}
    for kind in ("tpe", "random"):
        bests = []
        for seed in range(8):
            adv = make_advisor(_hard_config(), kind=kind, seed=seed)
            for _ in range(budget):
                knobs = adv.propose()
                adv.feedback(_hard_objective(knobs), knobs)
            bests.append(adv.best()[1])
        results[kind] = float(np.mean(bests))
    assert results["tpe"] >= results["random"] + 0.15, results


def test_gp_advisor_concurrent_ask_tell():
    """k worker threads share ONE GpAdvisor (the scheduler's shape —
    SURVEY.md §7 'serialize ask/tell behind a lock'): no crash in _fit,
    history intact, best() monotone from every thread's view, pending
    liars drained, and a concurrent propose burst in the GP phase gets
    pushed apart by the constant-liar penalty."""
    import threading

    adv = GpAdvisor(_config(), seed=0, n_initial=4)
    k, rounds = 8, 10
    best_seqs = [[] for _ in range(k)]
    errors = []
    barrier = threading.Barrier(k)

    def run(i):
        try:
            barrier.wait()
            for _ in range(rounds):
                knobs = adv.propose()
                adv.feedback(_objective(knobs), knobs)
                best_seqs[i].append(adv.best()[1])
        except Exception as e:  # noqa: BLE001 — recorded for the assert
            errors.append(repr(e))

    threads = [threading.Thread(target=run, args=(i,)) for i in range(k)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()

    assert not errors, errors
    assert len(adv.history) == k * rounds  # no feedback lost
    for seq in best_seqs:
        assert all(a <= b + 1e-12 for a, b in zip(seq, seq[1:])), seq
    assert len(adv._pending) == 0  # every proposal was scored

    # Burst of concurrent proposals with no feedback in between: the
    # liar penalty must spread them (allow one collision — EI can
    # degenerate to a flat surface late in the search).
    burst = []
    burst_lock = threading.Lock()
    barrier2 = threading.Barrier(k)

    def burst_run():
        barrier2.wait()
        knobs = adv.propose()
        with burst_lock:
            burst.append(tuple(sorted(knobs.items())))

    threads = [threading.Thread(target=burst_run) for _ in range(k)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert len(set(burst)) >= k - 1, burst
