"""Observability plane (rafiki_tpu/obs/, docs/observability.md):
trace propagation through bus envelopes, the bounded on-disk journal
ring, the goodput ledger, the flight recorder, and the Prometheus
exposition (golden-file pinned).

Cross-PROCESS stitching is exercised by scripts/obs_smoke.py (real
spawned workers) and the chaos runner's journal-reconstruction checks;
these tests pin the in-process mechanics those builds sit on.
"""

import json
import os
from pathlib import Path

import pytest

from rafiki_tpu import telemetry
from rafiki_tpu.obs import context as trace_context
from rafiki_tpu.obs import journal as journal_mod
from rafiki_tpu.obs.journal import Journal, journal

GOLDEN = Path(__file__).parent / "data" / "prom_golden.txt"


@pytest.fixture
def journaled(tmp_path):
    """The process-global journal, configured into a tmp dir and
    guaranteed back to the unconfigured no-op afterwards."""
    journal.configure(tmp_path, role="test")
    try:
        yield tmp_path
    finally:
        journal.close()


# -- trace propagation -------------------------------------------------------


class _StubModel:
    def predict(self, queries):
        return [[0.6, 0.4] for _ in queries]


def test_trace_propagates_through_bus_envelope(journaled):
    """One traced predict batch: the SAME trace id must appear on the
    predictor's fan-out hop, the worker's pop hop, and the worker's
    forward span — the envelope carries it, not shared thread state."""
    import threading

    from rafiki_tpu.bus import InProcBus
    from rafiki_tpu.predictor import Predictor
    from rafiki_tpu.worker.inference import InferenceWorker

    bus = InProcBus()
    stop = threading.Event()
    worker = InferenceWorker(bus, "tp", "w1", _StubModel(), stop_event=stop)
    th = threading.Thread(target=worker.run, daemon=True)
    th.start()
    try:
        tid = "cafe" * 8
        with trace_context.trace(tid):
            out = Predictor(bus, "tp", timeout_s=5.0).predict([[1.0]])
        assert out and "error" not in str(out[0])
    finally:
        stop.set()
        th.join(timeout=5)

    records = journal_mod.read_dir(journaled)
    traced = [r for r in records if r.get("trace_id") == tid]
    names = {(r["kind"], r["name"]) for r in traced}
    assert ("bus", "add_query") in names
    assert ("bus", "pop_query") in names
    assert ("span", "inference.forward") in names
    # the stitched view is time-ordered and self-identifying
    for r in traced:
        assert r["pid"] == os.getpid()
        assert r["role"] == "test"
        assert r["ts"] > 0


def test_untraced_messages_stay_bare_tuples():
    """No active trace → 2-tuple envelopes (wire back-compat) and no
    journal side channel needed to serve."""
    from rafiki_tpu.bus import InProcBus

    bus = InProcBus()
    bus.add_worker("tp", "w1")
    assert trace_context.current_trace_id() is None
    bus.add_query("w1", "q1", [1.0])
    items = bus.pop_queries("w1", timeout=1.0)
    assert items == [("q1", [1.0])]


def test_trace_context_nesting_and_process_default():
    with trace_context.trace("a" * 32):
        assert trace_context.current_trace_id() == "a" * 32
        with trace_context.trace():  # inherits, does not mint
            assert trace_context.current_trace_id() == "a" * 32
    assert trace_context.current_trace_id() is None
    trace_context.set_process_trace("b" * 32)
    try:
        assert trace_context.current_trace_id() == "b" * 32
        with trace_context.trace("c" * 32):  # thread-local wins
            assert trace_context.current_trace_id() == "c" * 32
    finally:
        trace_context.set_process_trace(None)


# -- journal ring ------------------------------------------------------------


def test_journal_ring_rotates_and_stays_bounded(tmp_path):
    j = Journal(tmp_path, role="ring", max_records=10)
    try:
        for i in range(25):
            j.record("event", f"e{i}")
        live = j.path
        old = live.with_name(live.name + ".1")
        assert old.exists()
        n_live = sum(1 for _ in open(live))
        n_old = sum(1 for _ in open(old))
        # disk never holds more than 2x max lines, and exactly one
        # rotated generation exists (the older one was overwritten)
        assert n_live <= 10 and n_old <= 10
        assert len(list(tmp_path.glob("journal-*"))) == 2
        # the SURVIVING window is the newest records, across both files
        merged = journal_mod.read_dir(tmp_path)
        assert [r["name"] for r in merged] == [f"e{i}" for i in range(10, 25)]
        assert [r["name"] for r in j.tail(5)] == [f"e{i}" for i in range(20, 25)]
    finally:
        j.close()


def test_journal_unconfigured_is_noop_and_reader_skips_torn_lines(tmp_path):
    j = Journal()
    j.record("event", "dropped")  # must not raise, must not write
    assert j.path is None
    # a crashed writer leaves a torn final line; readers skip it
    p = tmp_path / "journal-x-1.jsonl"
    p.write_text(json.dumps({"ts": 1.0, "name": "ok"}) + "\n" + '{"ts": 2.0, "na')
    assert [r["name"] for r in journal_mod.read_dir(tmp_path)] == ["ok"]


def test_spans_flush_into_journal(journaled):
    with telemetry.span("obs.test_phase"):
        pass
    recs = [r for r in journal_mod.read_dir(journaled)
            if r["kind"] == "span" and r["name"] == "obs.test_phase"]
    assert len(recs) == 1
    assert recs[0]["dur_s"] >= 0


# -- goodput ledger ----------------------------------------------------------


def test_ledger_entities_and_goodput_rollup():
    from rafiki_tpu.obs.ledger import ledger

    ledger.reset()
    try:
        with ledger.entity("trial:t1"):
            ledger.add("compile_s", 3.0)
            ledger.add("step_s", 1.0)
        ledger.add("downtime_s", 2.0, entity="job:j1")
        snap = ledger.snapshot()
        t1 = snap["entities"]["trial:t1"]
        assert t1["compile_s"] == 3.0 and t1["step_s"] == 1.0
        assert t1["wall_s"] > 0
        assert snap["entities"]["job:j1"]["downtime_s"] == 2.0
        assert snap["total"]["compile_s"] == 3.0
        assert snap["goodput"] == pytest.approx(
            1.0 / snap["total"]["wall_s"], rel=1e-3)
        # rides along in every telemetry snapshot (GET /metrics)
        assert telemetry.snapshot()["goodput"]["total"]["step_s"] == 1.0
    finally:
        ledger.reset()


# -- flight recorder ---------------------------------------------------------


def test_flight_recorder_dump(journaled):
    from rafiki_tpu.obs import recorder

    journal.record("event", "before_crash")
    with trace_context.trace("d" * 32):
        path = recorder.dump("test_reason", extra={"detail": "x"})
    assert path is not None and path.exists()
    payload = json.loads(path.read_text())
    assert payload["reason"] == "test_reason"
    assert payload["role"] == "test"
    assert payload["trace_id"] == "d" * 32
    assert payload["detail"] == "x"
    assert any(r["name"] == "before_crash" for r in payload["journal_tail"])
    assert "counters" in payload["telemetry"]
    # the dump itself is journaled, so `obs tail` shows the crash marker
    assert any(r["kind"] == "flight" for r in journal.tail(8))


def test_flight_recorder_without_log_dir_is_noop(tmp_path, monkeypatch):
    from rafiki_tpu.obs import recorder

    monkeypatch.delenv(journal_mod.ENV_VAR, raising=False)
    assert journal.log_dir is None or not journal.configured
    if journal.log_dir is None:
        assert recorder.dump("nowhere") is None


# -- CLI ---------------------------------------------------------------------


def test_obs_cli_trace_and_tail(journaled, capsys):
    from rafiki_tpu.obs import cli

    tid = "beef" * 8
    with trace_context.trace(tid):
        journal.record("event", "hop1")
        journal.record("event", "hop2")
    journal.record("event", "unrelated")

    assert cli.main(["--dir", str(journaled), "trace", tid]) == 0
    out = capsys.readouterr().out
    assert "hop1" in out and "hop2" in out and "unrelated" not in out
    assert "2 records" in out

    # prefix match works (operators paste truncated ids)
    assert cli.main(["--dir", str(journaled), "--json", "trace", tid[:8]]) == 0
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    assert {r["trace_id"] for r in lines} == {tid}

    assert cli.main(["--dir", str(journaled), "tail", "-n", "1"]) == 0
    assert "unrelated" in capsys.readouterr().out

    # unknown trace: exit 1, message on stderr
    assert cli.main(["--dir", str(journaled), "trace", "f" * 32]) == 1


# -- Prometheus exposition ---------------------------------------------------

#: A fixed, fully-populated snapshot: every branch of the renderer —
#: counters, gauges, histogram summaries, span aggregates, collector
#: flattening (numeric kept, strings dropped), name sanitization and
#: label escaping.
_SNAPSHOT = {
    "ts": 1700000000.0,
    "counters": {"gateway.shed": 3, "bus.queries_added": 12.0,
                 "serving.microbatch.flush_size": 2,
                 "gateway.blackout_retries": 1.0,
                 "serving.tenant.admitted": 20,
                 "serving.tenant.shed": 5,
                 "serving.tenant.shed_batch": 5,
                 "tenant.accounting_evictions": 1,
                 "tenancy.residency_hits": 7,
                 "tenancy.residency_misses": 3,
                 "tenancy.residency_evictions": 2,
                 "tenancy.host_queries": 10.0,
                 "tenancy.jobs_admitted": 2,
                 "tenancy.jobs_rejected": 1},
    "gauges": {"bus.queue_depth": 2, "serving.qps": 18.0,
               "serving.tenant.burn": 0.4765,
               "tenancy.residency_used_bytes": 160},
    "histograms": {
        "predictor.gather_s": {"count": 4, "sum": 0.5, "p50": 0.1,
                               "p90": 0.2, "p99": 0.25},
        "serving.hop.forward_s": {"count": 9, "sum": 0.09, "p50": 0.01,
                                  "p90": 0.012, "p99": 0.02},
        "serving.fanout_cost_s": {"count": 4, "sum": 0.02, "p50": 0.004,
                                  "p90": 0.006, "p99": 0.008},
        "serving.microbatch.size": {"count": 2, "sum": 6.0, "p50": 3.0,
                                    "p90": 4.0, "p99": 4.0},
        "serving.microbatch.fill_ratio": {"count": 2, "sum": 1.5,
                                          "p50": 0.75, "p90": 1.0,
                                          "p99": 1.0},
        "serving.hop.gateway_batch_wait_s": {"count": 4, "sum": 0.012,
                                             "p50": 0.003, "p90": 0.005,
                                             "p99": 0.006},
    },
    "spans": {
        'trial "quoted"': {"count": 2, "total_s": 1.5},
        "worker.epoch": {"count": 8, "total_s": 4.0},
    },
    "goodput": {
        "total": {"step_s": 1.0, "wall_s": 4.0},
        "goodput": 0.25,
        "note": "strings have no prometheus representation",
    },
    "perf": {
        "n_programs": 1,
        "programs": {"8c2d3ca7df": {"k": 1, "epochs": 4,
                                    "step_p50_s": 0.005, "mfu": 0.41,
                                    "kind": "strings are dropped"}},
    },
    "slo": {
        "specs": 2,
        "breaching": 1,
        "state": {"step_anomaly_rate": {"breaching": 1, "threshold": 0.05,
                                        "value": 0.2, "burn": 4.0}},
    },
    "health": {
        "divergences": 1,
        "capsules": 1,
        "evictions": 0,
        "contained": 1,
        "badput_charged_s": 2.25,
    },
    "serving": {
        "buckets_flushed": 3,
        "last": {"bucket": 1700000000, "requests": 18, "qps": 18.0,
                 "p50_ms": 11.5, "p99_ms": 40.25, "shed_rate": 0.0,
                 "context_note": "strings are dropped"},
    },
    "serving_exemplars": {
        "retained": 2,
        "offered": 18,
        "windows_flushed": 1,
        "cap": 8,
        "window_s": 30.0,
        "slowest_s": 0.040251,
    },
    "search": {
        "n_proposed": 12,
        "n_scored": 9,
        "n_doomed": 2,
        "n_pending": 1,
        "scored_wall_s": 54.0,
        "doomed_wall_s": 6.0,
        "elapsed_s": 60.0,
        "effective_trials_per_hour": 540.0,
        "regret": 0.0834,
        "best_score": 0.91,
        "n_killed": 2,
        "n_false_kills": 0,
        "n_speculations": 3,
        "n_corrections": 2,
    },
}


def test_prometheus_exposition_matches_golden():
    from rafiki_tpu.obs import prom

    rendered = prom.to_prometheus(_SNAPSHOT)
    assert rendered == GOLDEN.read_text(), (
        "Prometheus exposition drifted from tests/data/prom_golden.txt — "
        "if the change is intentional, regenerate the golden file:\n"
        "  python -c 'from tests.test_obs import _SNAPSHOT; "
        "from rafiki_tpu.obs import prom; "
        "print(prom.to_prometheus(_SNAPSHOT), end=\"\")' "
        "> tests/data/prom_golden.txt")


def test_prometheus_exposition_is_deterministic_and_parseable():
    import re

    from rafiki_tpu.obs import prom

    telemetry.reset()
    try:
        telemetry.inc("obs.test_counter", 2)
        with telemetry.span("obs.prom_span"):
            pass
        text = prom.to_prometheus(telemetry.snapshot())
        assert text == prom.to_prometheus(telemetry.snapshot())
        sample = re.compile(
            r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9.e+-]+$')
        for line in text.splitlines():
            assert line.startswith("# TYPE ") or sample.match(line), line
        assert "rafiki_obs_test_counter 2" in text
    finally:
        telemetry.reset()
