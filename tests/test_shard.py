"""Sharded-trial lane (docs/sharding.md): plan math, the width-
invariance contract of ShardedTrainLoop, and reshard-on-restore.

The load-bearing invariant everything downstream leans on (the chaos
scenario's unfaulted-run comparison, the GroupHandle re-form path):
the sharded loop is BIT-IDENTICAL to the serial loop at any width —
gather → serial scan body → reslice commutes with the sharding. These
tests pin that, plus the checkpoint manifest's failure modes: a
missing chunk and a doctored wrong-width chunk must fail loudly,
naming the chunk.
"""

import json
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from rafiki_tpu.shard import (ShardPlan, ShardedTrainLoop, gather_state,
                              is_manifest, restore_sharded, save_sharded,
                              shard_axis, solve_width)
from rafiki_tpu.store.params import ParamsStore

BATCH = 8
EPOCHS = 2
SEED = 3


# ---------------------------------------------------------------------------
# plan math
# ---------------------------------------------------------------------------


def test_shard_axis_is_largest_divisible_axis():
    assert shard_axis((16, 4), 2) == 0
    assert shard_axis((4, 16), 2) == 1
    assert shard_axis((6, 8), 4) == 1     # 6 % 4 != 0
    assert shard_axis((3, 5), 2) is None  # nothing divisible
    assert shard_axis((), 2) is None      # scalar replicates
    assert shard_axis((16,), 1) is None   # width 1 shards nothing


def test_solve_width_smallest_power_of_two_under_ceiling(monkeypatch):
    from rafiki_tpu.obs.twin.calibration import HBM_BYTES_PER_CHIP

    monkeypatch.delenv("RAFIKI_SHARD_WIDTH", raising=False)
    assert solve_width(int(0.5 * HBM_BYTES_PER_CHIP)) == 1
    assert solve_width(int(1.5 * HBM_BYTES_PER_CHIP)) == 2
    assert solve_width(int(3.0 * HBM_BYTES_PER_CHIP)) == 4
    # the cap clamps even when the estimate wants more
    assert solve_width(int(100 * HBM_BYTES_PER_CHIP), cap=4) == 4
    # the env pin overrides the solve entirely
    monkeypatch.setenv("RAFIKI_SHARD_WIDTH", "2")
    assert solve_width(int(100 * HBM_BYTES_PER_CHIP)) == 2


def test_plan_specs_follow_the_axis_rule():
    from jax.sharding import PartitionSpec as P

    plan = ShardPlan(width=2, family="t")
    assert plan.spec_of((16, 4)) == P("shard")
    assert plan.spec_of((4, 16)) == P(None, "shard")
    assert plan.spec_of(()) == P()
    tree = {"w": jax.ShapeDtypeStruct((16, 4), jnp.float32),
            "b": jax.ShapeDtypeStruct((3,), jnp.float32)}
    specs = plan.spec_tree(tree)
    assert specs["w"] == P("shard") and specs["b"] == P()


# ---------------------------------------------------------------------------
# the lane: width invariance + reshard round-trips
# ---------------------------------------------------------------------------


class _DS:
    def __init__(self, n=64, d=8, classes=4, seed=0):
        rng = np.random.default_rng(seed)
        self.x = rng.normal(size=(n, d)).astype(np.float32)
        self.y = rng.integers(0, classes, size=(n,)).astype(np.int32)
        self.size = n
        self.mask = None


def _loop_fns():
    import flax.linen as nn
    import optax

    class Mlp(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(nn.relu(nn.Dense(16)(x)))

    m = Mlp()

    def init_fn(rng):
        return m.init(rng, jnp.zeros((1, 8), jnp.float32))

    def apply_fn(p, x):
        return m.apply(p, x)

    def loss_fn(p, batch, rng=None):
        logits = apply_fn(p, batch["x"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()
        return loss, {"acc": (logits.argmax(-1) == batch["y"]).mean()}

    return init_fn, apply_fn, loss_fn


def _flat(state):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(
        gather_state(state))]


def _bitmatch(a, b):
    la, lb = _flat(a), _flat(b)
    assert len(la) == len(lb)
    return all(x.dtype == y.dtype and np.array_equal(x, y)
               for x, y in zip(la, lb))


@pytest.fixture(scope="module")
def lane():
    """Loops at widths 1/2/4 plus the serial reference, all trained
    EPOCHS epochs on the same data/seed (one fixture — the compiles
    dominate, so every test shares them)."""
    init_fn, apply_fn, loss_fn = _loop_fns()
    ds = _DS()
    devs = jax.devices()
    loops = {}
    for w in (1, 2, 4):
        loop = ShardedTrainLoop(
            init_fn, apply_fn, loss_fn, devices=devs[:w], seed=SEED,
            plan=ShardPlan(width=w, family="mlp"),
            program_key=("test_shard", "mlp"))
        for ep in range(EPOCHS):
            metrics = loop.run_epoch(ds, BATCH, epoch_seed=SEED + ep)
        loops[w] = (loop, metrics)
    from rafiki_tpu.ops.train import TrainLoop

    serial = TrainLoop(init_fn, apply_fn, loss_fn, seed=SEED,
                       program_key=("test_shard", "mlp"))
    for ep in range(EPOCHS):
        serial_metrics = serial.run_epoch(ds, BATCH, epoch_seed=SEED + ep)
    return {"loops": loops, "serial": serial,
            "serial_metrics": serial_metrics, "ds": ds}


def test_width1_loop_is_byte_identical_to_serial(lane):
    loop, metrics = lane["loops"][1]
    assert metrics["loss"] == lane["serial_metrics"]["loss"]
    assert _bitmatch(loop.state, lane["serial"].state)


@pytest.mark.parametrize("width", [2, 4])
def test_wider_groups_bitmatch_width1(lane, width):
    loop1, m1 = lane["loops"][1]
    loopw, mw = lane["loops"][width]
    assert mw["loss"] == m1["loss"]
    assert _bitmatch(loopw.state, loop1.state)


@pytest.mark.parametrize("from_w,to_w", [(1, 2), (2, 1), (2, 4)])
def test_reshard_roundtrip_bitmatches(lane, from_w, to_w):
    src, _ = lane["loops"][from_w]
    dst, _ = lane["loops"][to_w]
    with tempfile.TemporaryDirectory() as d:
        store = ParamsStore(d)
        save_sharded(store, "t1", EPOCHS - 1, src.state, src.width)
        epoch, blob = store.latest_checkpoint("t1")
        assert epoch == EPOCHS - 1 and is_manifest(blob)
        restored = restore_sharded(store, blob, dst.state, dst.mesh,
                                   dst.plan)
    assert _bitmatch(restored, src.state)


def test_missing_chunk_fails_naming_the_chunk(lane):
    src, _ = lane["loops"][2]
    with tempfile.TemporaryDirectory() as d:
        store = ParamsStore(d)
        save_sharded(store, "t1", 0, src.state, 2)
        _epoch, blob = store.latest_checkpoint("t1")
        man = json.loads(blob.decode())
        man["shards"][1] = "t1_ckpt_0_s1of2_GONE"
        doctored = json.dumps(man).encode()
        with pytest.raises(IOError, match="t1_ckpt_0_s1of2_GONE"):
            restore_sharded(store, doctored, src.state, src.mesh,
                            src.plan)


def test_doctored_wrong_width_chunk_is_caught(lane):
    # A width-4 chunk spliced into a width-2 manifest: every sharded
    # leaf in it is a 1/4 slice where the manifest promises 1/2 — the
    # reader must refuse, naming the chunk.
    src2, _ = lane["loops"][2]
    src4, _ = lane["loops"][4]
    with tempfile.TemporaryDirectory() as d:
        store = ParamsStore(d)
        save_sharded(store, "a", 0, src2.state, 2)
        save_sharded(store, "b", 0, src4.state, 4)
        _epoch, blob = store.latest_checkpoint("a")
        man = json.loads(blob.decode())
        man["shards"][0] = "b_ckpt_0_s0of4"
        doctored = json.dumps(man).encode()
        with pytest.raises(IOError, match="b_ckpt_0_s0of4"):
            restore_sharded(store, doctored, src2.state, src2.mesh,
                            src2.plan)


def test_inconsistent_manifest_width_is_refused(lane):
    from rafiki_tpu.shard import load_manifest

    src, _ = lane["loops"][2]
    with tempfile.TemporaryDirectory() as d:
        store = ParamsStore(d)
        save_sharded(store, "t1", 0, src.state, 2)
        _epoch, blob = store.latest_checkpoint("t1")
        man = json.loads(blob.decode())
        man["width"] = 3  # claims 3, lists 2 chunks
        with pytest.raises(IOError, match="wrong-width"):
            load_manifest(json.dumps(man).encode())
        with pytest.raises(IOError, match="wrong format"):
            load_manifest(b'{"format": "not-a-manifest"}')


def test_serial_checkpoints_are_not_mistaken_for_manifests():
    assert not is_manifest(b"\x80\x05...pickled")
    assert not is_manifest(b'{"format": "other"}')
