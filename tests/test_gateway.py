"""Serving gateway: admission control, quorum gather, breakers, drain.

The acceptance scenario (ISSUE 3) runs deterministically on the
in-proc bus: k=3 workers where one is a *fresh-leased corpse* — the
in-proc stand-in for a SIGKILLed process, registered and heartbeating
but never serving (the real-SIGKILL variant lives in
tests/test_serve_elastic.py) — under offered load above the inflight
budget. The gateway must shed the overflow with 429s, answer every
admitted request within its deadline via quorum gather, and report
consistent counts on ``GET /gateway`` and ``/metrics``.
"""

import threading
import time

import pytest
from werkzeug.test import Client

from rafiki_tpu import telemetry
from rafiki_tpu.bus import InProcBus, make_mp_bus
from rafiki_tpu.gateway import (
    AdmissionController, CircuitBreaker, Gateway, GatewayConfig, ShedError)
from rafiki_tpu.predictor import Predictor
from rafiki_tpu.predictor.app import PredictorApp
from rafiki_tpu.worker.inference import InferenceWorker

JOB = "gwjob"


class _SlowConst:
    """Stand-in model: fixed prob vector after a fixed service time."""

    def __init__(self, vec, delay_s=0.0):
        self.vec = list(vec)
        self.delay_s = delay_s

    def predict(self, queries):
        if self.delay_s:
            time.sleep(self.delay_s)
        return [self.vec for _ in queries]


class _Serving:
    """k live in-proc workers plus (optionally) one fresh-leased corpse
    that never answers — registered, heartbeating, dead to queries."""

    def __init__(self, models, corpse=None, job=JOB):
        self.bus = InProcBus()
        self.job = job
        self.stop = threading.Event()
        self.threads = []
        for i, model in enumerate(models):
            w = InferenceWorker(self.bus, job, f"w{i}", model,
                                stop_event=self.stop)
            th = threading.Thread(target=w.run, daemon=True)
            self.threads.append(th)
            th.start()
        deadline = time.monotonic() + 10
        while len(self.bus.get_workers(job)) < len(models):
            assert time.monotonic() < deadline, "workers never registered"
            time.sleep(0.005)
        self.corpse = corpse
        if corpse is not None:
            self.bus.add_worker(job, corpse)
            th = threading.Thread(target=self._beat_corpse, daemon=True)
            self.threads.append(th)
            th.start()

    def _beat_corpse(self):
        while not self.stop.wait(0.2):
            self.bus.heartbeat(self.job, self.corpse)

    def close(self):
        self.stop.set()
        for th in self.threads:
            th.join(timeout=2)


def _no_errors(preds):
    return all(not (isinstance(p, dict) and "error" in p) for p in preds)


# -- the acceptance scenario -------------------------------------------------


def test_gateway_sheds_and_answers_admitted_within_deadline():
    """k=3 (one fresh-leased corpse), offered load > inflight budget:
    (a) overflow shed with 429 + Retry-After, (b) every admitted
    request answered within its deadline with NO timeout errors,
    (c) /gateway and /metrics agree on admitted/shed/hedged and show
    the corpse's breaker tripping."""
    telemetry.reset()
    serving = _Serving([_SlowConst([0.8, 0.2], 0.05),
                        _SlowConst([0.6, 0.4], 0.05)], corpse="stuck")
    try:
        predictor = Predictor(serving.bus, JOB, timeout_s=5.0)
        gateway = Gateway(predictor, GatewayConfig(
            max_inflight=1, max_queue=1, hedge_grace_s=0.05,
            breaker_failures=3))
        app = Client(PredictorApp(gateway))

        deadline_s = 4.0
        offered = 12
        results = []
        results_lock = threading.Lock()

        def fire():
            t0 = time.monotonic()
            r = app.post("/predict",
                         json={"queries": [[1.0]], "deadline_s": deadline_s})
            with results_lock:
                results.append((r.status_code, time.monotonic() - t0,
                                r.get_json(), dict(r.headers)))

        threads = [threading.Thread(target=fire) for _ in range(offered)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()

        codes = sorted(c for c, _, _, _ in results)
        assert codes.count(429) >= 1, f"nothing shed: {codes}"
        assert codes.count(200) >= 1, f"nothing admitted: {codes}"
        assert set(codes) <= {200, 429}, codes
        for code, dt, body, headers in results:
            if code == 200:
                # Admitted ⇒ answered within the deadline via quorum
                # gather — never a "prediction timeout" masquerading
                # as an answer, never a blown deadline.
                assert dt < deadline_s, f"admitted request took {dt:.2f}s"
                assert _no_errors(body["predictions"]), body
            else:
                assert "Retry-After" in headers
                assert int(headers["Retry-After"]) >= 1

        # Force the corpse's breaker open with a few sequential batches.
        for _ in range(3):
            assert app.post("/predict",
                            json={"queries": [[1.0]]}).status_code == 200

        stats = app.get("/gateway").get_json()
        snap = app.get("/metrics").get_json()
        assert stats["admitted"] == snap["counters"]["gateway.admitted"]
        assert stats["shed_total"] == snap["counters"]["gateway.shed"]
        assert stats["hedged"] == snap["counters"].get("gateway.hedged", 0)
        assert stats["timeouts"] == 0
        assert stats["admitted"] + stats["shed_total"] == offered + 3
        # While the corpse was still in the fan-out, quorum (2 of 3) +
        # grace closed those gathers early — hedging happened.
        assert stats["hedged"] >= 1
        stuck = stats["breakers"]["stuck"]
        assert stuck["failures"] >= 3
        assert stuck["state"] == "open"
        # /metrics carries the same breaker state via the collector.
        assert snap["gateway"]["breakers"]["stuck"]["state"] == "open"
        assert snap["counters"]["gateway.breaker_opened"] >= 1
    finally:
        serving.close()


# -- admission ---------------------------------------------------------------


def test_admission_deadline_shed():
    ac = AdmissionController(max_inflight=1, max_queue=4)
    assert ac.admit(time.monotonic() + 1.0) == 0.0
    with pytest.raises(ShedError) as e:
        ac.admit(time.monotonic() + 0.05)
    assert e.value.reason == "deadline"
    ac.release()
    assert ac.inflight == 0


def test_admission_queue_full_shed():
    ac = AdmissionController(max_inflight=1, max_queue=0)
    ac.admit(time.monotonic() + 1.0)
    with pytest.raises(ShedError) as e:
        ac.admit(time.monotonic() + 1.0)
    assert e.value.reason == "queue_full"
    ac.release()


def test_admission_waiter_gets_freed_slot():
    ac = AdmissionController(max_inflight=1, max_queue=1)
    ac.admit(time.monotonic() + 5.0)
    got = []

    def wait_for_slot():
        got.append(ac.admit(time.monotonic() + 5.0))

    th = threading.Thread(target=wait_for_slot)
    th.start()
    time.sleep(0.05)
    assert not got  # still queued
    ac.release()
    th.join(timeout=2)
    assert len(got) == 1 and got[0] > 0  # waited, then admitted
    ac.release()


# -- circuit breaker ---------------------------------------------------------


def test_breaker_open_half_open_close_transitions():
    now = [0.0]
    br = CircuitBreaker(failure_threshold=2, cooldown_s=5.0,
                        clock=lambda: now[0])
    assert br.allow() and br.state == "closed"
    br.record_failure()
    assert br.state == "closed"  # below threshold
    br.record_failure()
    assert br.state == "open"
    assert not br.allow()
    now[0] = 5.1  # cooldown elapsed → half-open, exactly one probe
    assert br.allow()
    assert br.state == "half-open"
    assert not br.allow()  # second probe refused while first is out
    br.record_failure()  # probe missed → reopen for a full cooldown
    assert br.state == "open"
    assert not br.allow()
    now[0] = 10.3
    assert br.allow()
    br.record_success()
    assert br.state == "closed"
    assert br.allow()
    snap = br.snapshot()
    assert snap["failures"] == 3 and snap["successes"] == 1


# -- routing -----------------------------------------------------------------


def test_least_loaded_routes_to_emptiest_worker():
    serving = _Serving([_SlowConst([1.0, 0.0])])  # w0: the live worker
    try:
        bus = serving.bus
        # A second registered worker with a backlog: least-loaded must
        # route around it (and with quorum 1, its silence is harmless).
        bus.add_worker(JOB, "busy")
        bus.add_query("busy", "preload-1", [0.0])
        bus.add_query("busy", "preload-2", [0.0])
        predictor = Predictor(bus, JOB, timeout_s=2.0)
        gateway = Gateway(predictor,
                          GatewayConfig(policy="least-loaded"))
        out = gateway.predict([[1.0], [2.0]])
        assert out == [[1.0, 0.0], [1.0, 0.0]]  # w0's vector, no ensemble
        assert bus.queue_depth("busy") == 2  # nothing new routed to it
    finally:
        serving.close()


# -- drain -------------------------------------------------------------------


def test_drain_flushes_inflight_and_sheds_new():
    serving = _Serving([_SlowConst([0.5, 0.5], 0.3)])
    try:
        predictor = Predictor(serving.bus, JOB, timeout_s=5.0)
        gateway = Gateway(predictor, GatewayConfig(max_inflight=2))
        app = Client(PredictorApp(gateway))
        inflight_result = []

        def inflight_request():
            inflight_result.append(
                app.post("/predict", json={"queries": [[1.0]]}))

        th = threading.Thread(target=inflight_request)
        th.start()
        time.sleep(0.1)  # let it get admitted into the slow forward
        assert gateway.drain(timeout=5.0), "inflight never flushed"
        th.join(timeout=5)
        # The admitted request ran to completion through the drain.
        assert inflight_result[0].status_code == 200
        # New arrivals shed as draining (503 at the HTTP layer) and
        # health flips.
        r = app.post("/predict", json={"queries": [[1.0]]})
        assert r.status_code == 503
        assert r.get_json()["reason"] == "draining"
        h = app.get("/healthz")
        assert h.status_code == 503
        assert h.get_json()["status"] == "draining"
        assert app.get("/gateway").get_json()["draining"] is True
    finally:
        serving.close()


# -- HTTP request validation -------------------------------------------------


def test_predict_request_limits_and_malformed_bodies():
    serving = _Serving([_SlowConst([0.5, 0.5])])
    try:
        predictor = Predictor(serving.bus, JOB, timeout_s=2.0)
        gateway = Gateway(predictor,
                          GatewayConfig(max_queries_per_request=4))
        app = Client(PredictorApp(gateway))
        assert app.post("/predict",
                        json={"queries": [[1.0]]}).status_code == 200
        # Over the per-request cap → 413, never fanned out.
        assert app.post("/predict",
                        json={"queries": [[1.0]] * 5}).status_code == 413
        # Malformed bodies stay 400: non-JSON, non-dict JSON,
        # missing/non-list queries, junk deadline.
        assert app.post("/predict", data="{[",
                        content_type="application/json").status_code == 400
        assert app.post("/predict", json=[1, 2]).status_code == 400
        assert app.post("/predict", json={"queries": "x"}).status_code == 400
        assert app.post("/predict",
                        json={"queries": [[1.0]],
                              "deadline_s": "soon"}).status_code == 400
        assert app.post("/predict",
                        json={"queries": [[1.0]],
                              "deadline_s": -1}).status_code == 400
    finally:
        serving.close()


# -- bus satellites ----------------------------------------------------------


def test_inproc_bus_depth_counter_tracks_queue():
    bus = InProcBus()
    bus.add_worker("j", "w")
    for i in range(3):
        bus.add_query("w", f"q{i}", [float(i)])
    assert bus.queue_depth("w") == 3
    assert telemetry.get_gauge("bus.queue_depth") == 3
    items = bus.pop_queries("w", max_n=64, timeout=0.1)
    assert len(items) == 3
    assert bus.queue_depth("w") == 0
    # Dropping a worker with a backlog must not strand the counter.
    bus.add_query("w", "q3", [3.0])
    bus.remove_worker("j", "w")
    assert bus._depth == 0


def test_mp_bus_expired_trim_is_insertion_ordered():
    """Regression for the coarse `self._expired.clear()`: overflowing
    the expiry cap must forget only the OLDEST ids — recently expired
    queries keep rejecting late answers."""
    bus = make_mp_bus()
    bus._expired_cap = 8
    for i in range(9):  # expire 9 ids through a cap of 8
        bus.get_predictions(f"q{i}", n=1, timeout=0)
    # Recent ids are still guarded: a late answer is dropped...
    bus.put_prediction("q8", "w", [1.0])
    assert bus._preds.get("q8", ()) == ()
    bus.put_prediction("q1", "w", [1.0])
    assert bus._preds.get("q1", ()) == ()
    # ...while only the single oldest id (q0) was trimmed and re-leaks
    # one slot, the documented cost of the bounded window.
    bus.put_prediction("q0", "w", [1.0])
    assert len(bus._preds.get("q0", ())) == 1


# -- quorum gather on the in-proc bus ----------------------------------------


def test_quorum_gather_returns_before_straggler_deadline():
    """Wait-for-quorum + hedge grace: with one silent replica, the
    gather closes in ~grace time, not the full timeout."""
    serving = _Serving([_SlowConst([0.8, 0.2]), _SlowConst([0.6, 0.4])],
                       corpse="stuck")
    try:
        predictor = Predictor(serving.bus, JOB, timeout_s=5.0)
        t0 = time.monotonic()
        report = predictor.predict_detailed(
            [[1.0]], min_replies=2, hedge_grace_s=0.1)
        dt = time.monotonic() - t0
        assert report.ok()
        assert dt < 2.0, f"quorum gather stalled on the corpse: {dt:.2f}s"
        assert report.hedged == 1
        assert report.replies.get("stuck", 0) == 0
        assert report.quorum == 2
        # Default (no quorum) still waits for all — here, the timeout.
        t0 = time.monotonic()
        full = predictor.predict_detailed([[1.0]], timeout_s=0.5)
        assert time.monotonic() - t0 >= 0.5
        assert full.ok()  # partial ensemble of the two live replies
    finally:
        serving.close()
