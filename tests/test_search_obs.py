"""Search anatomy plane (docs/search_anatomy.md): every advisor
decision leaves an audit record, sweeps reconstruct from journals
alone, trial lineage survives evict/backfill/repack/resume, and the
SWEEP_r* trend gates both ways."""

import json
import math
import os
import subprocess
import sys

import pytest

from rafiki_tpu.model.knobs import FixedKnob, FloatKnob, IntegerKnob
from rafiki_tpu.obs.journal import journal, read_dir
from rafiki_tpu.obs.search import audit, lineage, reconstruct, stats
from rafiki_tpu.obs.search.ledger import search_ledger

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

KC = {"lr": FloatKnob(1e-4, 3e-2, is_exp=True),
      "units": IntegerKnob(4, 64),
      "b": FixedKnob(8)}


def _objective(knobs):
    """One interior optimum — gives the GP something to exploit and the
    regret curve a real shape."""
    return round(1.0 - (math.log10(knobs["lr"]) + 2.5) ** 2 * 0.2
                 - abs(knobs["units"] - 32) / 64 * 0.2, 6)


@pytest.fixture()
def journaled(tmp_path):
    """Global journal into a tmp dir + a clean search ledger, both
    guaranteed back to pristine afterwards."""
    search_ledger.reset()
    journal.configure(tmp_path, role="test")
    try:
        yield tmp_path
    finally:
        journal.close()
        search_ledger.reset()


def _sweep(advisor, n=6):
    for _ in range(n):
        knobs = advisor.propose()
        advisor.feedback(_objective(knobs), knobs)


def _advisor(kind, seed=0, n_initial=3):
    from rafiki_tpu.advisor.gp import GpAdvisor
    from rafiki_tpu.advisor.random_advisor import RandomAdvisor
    from rafiki_tpu.advisor.tpe import TpeAdvisor

    if kind == "gp":
        return GpAdvisor(KC, seed=seed, n_initial=n_initial)
    if kind == "tpe":
        return TpeAdvisor(KC, seed=seed, n_initial=n_initial)
    return RandomAdvisor(KC, seed=seed)


# ---------------------------------------------------------------------------
# Decision audit completeness
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kind,phases", [
    ("gp", {"warmup", "ei"}),
    ("tpe", {"warmup", "tpe", "epsilon"}),
    ("random", {"random"}),
])
def test_audit_complete_per_engine(journaled, kind, phases):
    """Every propose and every feedback of every engine leaves a
    journal record carrying the acquisition 'why'."""
    adv = _advisor(kind, seed=3, n_initial=3)
    _sweep(adv, n=7)
    journal.close()
    recs = [r for r in read_dir(journaled) if r.get("kind") == "advisor"]
    proposes = [r for r in recs if r["name"] == "propose"]
    feedbacks = [r for r in recs if r["name"] == "feedback"]
    assert len(proposes) == 7 and len(feedbacks) == 7
    seen_phases = {p["acquisition"]["phase"] for p in proposes}
    assert seen_phases <= phases and "warmup" in seen_phases or kind == "random"
    assert all(p["engine"] == kind for p in proposes)
    assert all(p["knobs_hash"] == audit.knobs_hash(p["knobs"])
               for p in proposes)
    # feedback joins back to its proposal by hash, and best_so_far
    # includes the score it reports
    ph = [p["knobs_hash"] for p in proposes]
    assert all(f["knobs_hash"] in ph for f in feedbacks)
    assert all(f["best_so_far"] >= f["score"] for f in feedbacks)


def test_gp_ei_acquisition_internals(journaled):
    """Past warmup the GP must journal what it saw: EI of the chosen
    candidate, posterior mean/std, pool size, fit wall-time."""
    adv = _advisor("gp", seed=1, n_initial=3)
    _sweep(adv, n=6)
    journal.close()
    ei_recs = [r for r in read_dir(journaled)
               if r.get("kind") == "advisor" and r["name"] == "propose"
               and r["acquisition"]["phase"] == "ei"]
    assert ei_recs, "no post-warmup EI proposal was journaled"
    for r in ei_recs:
        acq = r["acquisition"]
        assert acq["ei"] >= 0 and acq["sigma"] >= 0
        assert acq["pool"] > 0 and acq["fit_s"] >= 0
        assert "mu" in acq


def test_propose_batch_journals_liar_state(journaled):
    adv = _advisor("gp", seed=2, n_initial=2)
    _sweep(adv, n=3)
    adv.propose_batch(3)
    journal.close()
    batches = [r for r in read_dir(journaled)
               if r.get("kind") == "advisor" and r["name"] == "propose_batch"]
    assert len(batches) == 1
    b = batches[0]
    assert b["n"] == 3 and len(b["knobs_hashes"]) == 3
    assert b["strategy"] == "constant_liar_min"
    assert b["liar"]["lies_planted"] == 3


# ---------------------------------------------------------------------------
# propose_batch over HTTP (satellite 1)
# ---------------------------------------------------------------------------


def _http_client():
    from werkzeug.test import Client
    from werkzeug.wrappers import Response

    from rafiki_tpu.advisor.app import AdvisorApp
    from rafiki_tpu.advisor.service import AdvisorService

    service = AdvisorService()
    aid = service.create_advisor(KC, kind="random", seed=0)
    return Client(AdvisorApp(service), Response), aid


def test_http_propose_batch_roundtrip(journaled):
    client, aid = _http_client()
    r = client.post(f"/advisors/{aid}/propose_batch", json={"n": 3})
    assert r.status_code == 200
    knobs_list = r.get_json()["knobs_list"]
    assert len(knobs_list) == 3
    assert all(set(k) == set(KC) for k in knobs_list)
    journal.close()
    recs = [r2 for r2 in read_dir(journaled) if r2.get("kind") == "advisor"]
    batches = [r2 for r2 in recs if r2["name"] == "propose_batch"]
    # journaled exactly like the in-proc path: one batch record whose
    # member hashes all have propose records, stamped with the registry id
    assert len(batches) == 1 and batches[0]["n"] == 3
    assert batches[0]["advisor_id"] == aid
    ph = [r2["knobs_hash"] for r2 in recs if r2["name"] == "propose"]
    assert all(h in ph for h in batches[0]["knobs_hashes"])


def test_http_propose_batch_rejects_bad_n(journaled):
    client, aid = _http_client()
    assert client.post(f"/advisors/{aid}/propose_batch",
                       json={"n": 0}).status_code == 400
    assert client.post(f"/advisors/{aid}/propose_batch",
                       json={}).status_code == 400
    assert client.post("/advisors/nope/propose_batch",
                       json={"n": 2}).status_code == 404


# ---------------------------------------------------------------------------
# Ledger: effective trials per hour, doomed accounting
# ---------------------------------------------------------------------------


def test_ledger_charges_doomed_wall_separately(journaled):
    from rafiki_tpu import telemetry

    adv = _advisor("random", seed=9)
    k1 = adv.propose()
    audit.note_doomed(k1)           # the worker's error path
    adv.feedback(0.0, k1)           # consolation feedback
    k2 = adv.propose()
    adv.feedback(0.8, k2)           # a real score
    journal.close()
    snap = search_ledger.snapshot()
    assert snap["n_proposed"] == 2
    assert snap["n_doomed"] == 1 and snap["n_scored"] == 1
    assert snap["best_score"] == 0.8
    assert snap["doomed_wall_s"] >= 0 and snap["scored_wall_s"] >= 0
    # the feedback record itself carries the doomed flag
    fb = [r for r in read_dir(journaled)
          if r.get("kind") == "advisor" and r["name"] == "feedback"]
    assert [f["doomed"] for f in fb] == [True, False]
    # and the telemetry gauges are live for prom/SLO consumers
    tsnap = telemetry.snapshot()
    assert tsnap["gauges"]["search.best_score"] == 0.8
    assert "search" in tsnap


# ---------------------------------------------------------------------------
# Reconstruction: regret, lift CI, reconciliation
# ---------------------------------------------------------------------------


def _two_engine_records(tmp_path, n=10):
    from rafiki_tpu.advisor.gp import GpAdvisor
    from rafiki_tpu.advisor.random_advisor import RandomAdvisor

    _sweep(GpAdvisor(KC, seed=5, n_initial=4), n=n)
    _sweep(RandomAdvisor(KC, seed=105), n=n)
    journal.close()
    return read_dir(tmp_path)


def test_reconstruct_regret_monotone_and_joined(journaled):
    recs = _two_engine_records(journaled)
    doc = reconstruct.reconstruct(recs)
    assert doc["engine"] == "gp" and doc["reconciliation"]["ok"]
    assert doc["n_proposals"] == 10 and doc["n_scored"] == 10
    best = doc["curve"]["best_so_far"]
    regret = doc["curve"]["regret"]
    assert all(a <= b for a, b in zip(best, best[1:]))
    assert all(a >= b for a, b in zip(regret, regret[1:]))
    assert regret[-1] == 0.0
    assert all(p["acquisition"]["phase"] for p in doc["proposals"])
    # lift vs the random baseline carries its bootstrap CI
    assert doc["lift"]["lo"] <= doc["advisor_lift"] <= doc["lift"]["hi"]


def test_reconstruct_lift_ci_deterministic(journaled):
    recs = _two_engine_records(journaled)
    a = reconstruct.reconstruct(recs, boot_seed=0)
    b = reconstruct.reconstruct(recs, boot_seed=0)
    assert a["lift"] == b["lift"]
    c = reconstruct.reconstruct(recs, boot_seed=1)
    assert c["lift"]["mean"] == a["lift"]["mean"]  # data-determined
    assert c["lift"] != a["lift"]                  # resamples are not


def test_bootstrap_ci_seeded_and_degenerate():
    d = [0.1, -0.2, 0.3, 0.05, 0.0]
    assert stats.bootstrap_ci(d, seed=7) == stats.bootstrap_ci(d, seed=7)
    ci = stats.bootstrap_ci(d, seed=7)
    assert ci["lo"] <= ci["mean"] <= ci["hi"]
    empty = stats.bootstrap_ci([])
    assert empty["n"] == 0 and empty["mean"] is None
    one = stats.bootstrap_ci([0.4])
    assert one["mean"] == one["lo"] == one["hi"] == 0.4


def test_reconciliation_fails_on_unjournaled_decision(journaled):
    recs = _two_engine_records(journaled)
    cut = next(i for i, r in enumerate(recs)
               if r.get("kind") == "advisor" and r["name"] == "propose"
               and r.get("engine") == "gp")
    doctored = recs[:cut] + recs[cut + 1:]
    doc = reconstruct.reconstruct(doctored)
    assert not doc["reconciliation"]["ok"]
    errs = doc["reconciliation"]["errors"]
    assert any(e["type"] == "feedback_without_propose" for e in errs)
    # and the artifact slice refuses to look like a healthy round
    art = reconstruct.artifact(doc)
    assert art["error"] == "sweep reconciliation failed"


def test_artifact_slice_is_trendable(journaled):
    recs = _two_engine_records(journaled)
    art = reconstruct.artifact(reconstruct.reconstruct(recs))
    assert art["sweep_schema_version"] == reconstruct.SWEEP_SCHEMA_VERSION
    assert "error" not in art
    for k in ("best_score", "regret", "advisor_lift",
              "lift_ci_low", "lift_ci_high"):
        assert k in art, k


# ---------------------------------------------------------------------------
# Lineage across evict + backfill and repack + resume
# ---------------------------------------------------------------------------


def test_lineage_evict_and_backfill(journaled, monkeypatch):
    from rafiki_tpu import telemetry
    from rafiki_tpu.advisor import AdvisorService
    from rafiki_tpu.chaos.scenarios import EVICT_SOURCE
    from rafiki_tpu.model.base import load_model_class
    from rafiki_tpu.model.knobs import knob_config_signature
    from rafiki_tpu.store import MetaStore, ParamsStore
    from rafiki_tpu.worker.train import (InProcAdvisorHandle,
                                         PackedTrialRunner, TrainWorker)
    from tests.test_scheduler import TRAIN, VAL

    telemetry.reset()
    store = MetaStore(journaled / "meta.sqlite3")
    params = ParamsStore(journaled / "params")
    model = store.create_model("evictff", "IMAGE_CLASSIFICATION", None,
                               EVICT_SOURCE, "EvictFF")
    job = store.create_train_job("searchobs", "IMAGE_CLASSIFICATION", None,
                                 TRAIN, VAL, {"MODEL_TRIAL_COUNT": 3})
    store.create_sub_train_job(job["id"], model["id"])
    sub = store.get_sub_train_jobs(job["id"])[0]
    cls = load_model_class(EVICT_SOURCE, "EvictFF")
    advisors = AdvisorService()
    aid = advisors.create_advisor(cls.get_knob_config(), kind="random")
    worker = TrainWorker(store, params, sub["id"], cls,
                         InProcAdvisorHandle(advisors, aid), TRAIN, VAL,
                         {"MODEL_TRIAL_COUNT": 3}, worker_id="evict-w0",
                         async_persist=False)
    kc = cls.get_knob_config()
    base = {"hidden_units": 16, "batch_size": 32, "epochs": 3}
    rows = []
    # lr >= 0.02 trips EvictFF's early-stop at epoch 0 (the straggler);
    # the freed slot is backfilled mid-pack — same shape as PR 7's
    # test_pack_straggler_evicted_and_backfilled.
    for kn in (dict(base, learning_rate=0.025),
               dict(base, learning_rate=0.005)):
        t = store.create_trial(sub["id"], "EvictFF", kn,
                               shape_sig=knob_config_signature(kc, kn),
                               budget_max=3)
        rows.append((t["id"], kn))
    assert PackedTrialRunner(worker, 2).run_assigned(rows, budget_max=3) == 3
    journal.close()
    trials = lineage.build(read_dir(journaled))
    assert len(trials) == 3
    assert sum(t["n_evictions"] for t in trials.values()) >= 1
    assert any(t["backfilled"] for t in trials.values()), \
        "the backfilled trial's lineage lost its origin"
    evicted = trials[rows[0][0]]
    assert evicted["n_evictions"] == 1
    # an evicted-but-scored member is a completed trial, not an orphan
    assert lineage.reconcile(trials) == []
    # and walk() resolves unique id prefixes like the CLI does
    assert lineage.walk(trials, rows[0][0][:8])["trial_id"] == rows[0][0]


def test_lineage_repack_resume_after_chip_loss(journaled, monkeypatch):
    from rafiki_tpu import telemetry
    from rafiki_tpu.chaos import FaultPlane, install, uninstall
    from rafiki_tpu.chaos.scenarios import FF_SOURCE as CHAOS_FF_SOURCE
    from rafiki_tpu.scheduler import MeshSweepScheduler
    from rafiki_tpu.store import MetaStore, ParamsStore
    from tests.test_scheduler import TRAIN, VAL

    telemetry.reset()
    # subprocess chip workers journal via RAFIKI_LOG_DIR; the
    # scheduler's own mesh/* records ride the fixture's journal
    monkeypatch.setenv("RAFIKI_LOG_DIR", str(journaled))
    monkeypatch.setenv("RAFIKI_CHECKPOINT_EVERY", "1")
    store = MetaStore(journaled / "meta.sqlite3")
    params = ParamsStore(journaled / "params")
    model = store.create_model("chaosff", "IMAGE_CLASSIFICATION", None,
                               CHAOS_FF_SOURCE, "ChaosFF")
    job = store.create_train_job("searchobs", "IMAGE_CLASSIFICATION", None,
                                 TRAIN, VAL, {"MODEL_TRIAL_COUNT": 4})
    store.create_sub_train_job(job["id"], model["id"])
    install(FaultPlane.from_spec(
        "seed=11;scheduler.preempt:kill:after=2:times=1:match=chip1"))
    try:
        result = MeshSweepScheduler(store, params).run_sweep(
            job["id"], chips=2, trials_per_chip=2, advisor_kind="random")
    finally:
        uninstall()
    journal.close()
    assert result.status == "COMPLETED", result.errors
    trials = lineage.build(read_dir(journaled))
    assert len(trials) == 4
    # the killed chip's rows moved: repack recorded, and at least one
    # trial restarted on the survivor (second incarnation or resume)
    moved = [t for t in trials.values() if t["repacked_from"]]
    assert moved, "mesh/repack left no lineage trace"
    assert any(t["n_incarnations"] > 1 or t["n_resumes"] >= 1
               for t in trials.values())
    # every incarnation accounted for: NO orphans fleet-wide
    assert lineage.reconcile(trials) == []
    statuses = {t["status"] for t in trials.values()}
    assert statuses == {"trial_completed"}, statuses


def test_lineage_reconcile_flags_orphans():
    """A started-never-terminated incarnation must surface loudly."""
    recs = [
        {"kind": "event", "name": "trial_started", "ts": 1.0,
         "trial_id": "t1", "worker_id": "w0", "knobs": {"lr": 0.1}},
        {"kind": "event", "name": "trial_completed", "ts": 2.0,
         "trial_id": "t1", "worker_id": "w0", "score": 0.5},
        {"kind": "event", "name": "trial_started", "ts": 1.5,
         "trial_id": "t2", "worker_id": "w1", "knobs": {"lr": 0.2}},
    ]
    trials = lineage.build(recs)
    orphans = lineage.reconcile(trials)
    assert [o["trial_id"] for o in orphans] == ["t2"]
    assert trials["t2"]["status"] == "orphaned"


# ---------------------------------------------------------------------------
# bench_report --sweep end to end (subprocess, both polarities)
# ---------------------------------------------------------------------------


def _report(args, cwd):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_report.py"),
         "--sweep", *args],
        capture_output=True, text=True, cwd=cwd, timeout=60)


def test_bench_report_sweep_gates_both_ways(journaled, tmp_path):
    recs = _two_engine_records(journaled)
    art = reconstruct.artifact(reconstruct.reconstruct(recs))

    def _round(n, doc):
        p = tmp_path / f"SWEEP_r{n:02d}.json"
        p.write_text(json.dumps(doc))
        return str(p)

    ok_rounds = [
        _round(1, dict(art, effective_trials_per_hour=400.0, regret=0.08)),
        _round(2, {"sweep_schema_version": 1,
                   "error": "sweep reconciliation failed"}),
        _round(3, dict(art, effective_trials_per_hour=420.0, regret=0.06)),
    ]
    ok = _report(ok_rounds, tmp_path)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    doc = json.loads(ok.stdout)
    assert doc["mode"] == "sweep" and doc["verdict"] == "ok"
    r02 = [r for r in doc["rounds"] if str(r["round"]).endswith("r02.json")]
    assert not r02[0]["has_data"], "an error round must be no-data"
    # negative advisor_lift is a measurement, not a dead backend
    assert doc["metrics"]["advisor_lift"]["n_measured"] == 2

    bad = _report(ok_rounds + [
        _round(4, dict(art, effective_trials_per_hour=150.0, regret=0.4))],
        tmp_path)
    assert bad.returncode == 1
    regressed = json.loads(bad.stdout)["regressed"]
    assert "effective_trials_per_hour" in regressed
    assert "regret" in regressed
