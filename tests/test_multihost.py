"""Multi-host DCN path: a dp train step really spanning 2 processes.

Exercises the previously-dead ``jax.distributed.initialize`` hook in
worker/main.py end to end: ProcessScheduler emits the coordinator env
for a 2-process worker group; process 0 (leader) runs the trial loop,
process 1 mirrors it (worker/follower.py); each process contributes 2
fake CPU devices, so every train step is a 4-device dp program whose
gradient all-reduce crosses the process boundary over the gloo
transport (DCN's stand-in on CPU). Completion is itself load-bearing
evidence: the leader's collectives BLOCK unless the follower joins
them — a dead DCN path hangs the job, it cannot quietly pass.
"""

import threading

import pytest

from rafiki_tpu.scheduler import ProcessScheduler
from rafiki_tpu.store import MetaStore, ParamsStore
from rafiki_tpu.utils.events import events

from tests.test_scheduler import FF_SOURCE, TRAIN, VAL


@pytest.fixture()
def env(tmp_path):
    store = MetaStore(tmp_path / "meta.sqlite3")
    params = ParamsStore(tmp_path / "params")
    model = store.create_model("tinyff", "IMAGE_CLASSIFICATION", None,
                               FF_SOURCE, "TinyFF")
    prev = events.path
    events.configure(tmp_path / "logs")
    yield store, params, model
    if prev is not None:
        events.configure(prev.parent)
    else:
        events._path = None
        events._fh = None


def test_multihost_dp_train_job(env):
    store, params, model = env
    job = store.create_train_job("mhapp", "IMAGE_CLASSIFICATION", None,
                                 TRAIN, VAL, {"MODEL_TRIAL_COUNT": 2})
    store.create_sub_train_job(job["id"], model["id"])
    sched = ProcessScheduler(store, params)
    result = sched.run_train_job(job["id"], n_workers=1, devices_per_trial=2,
                                 advisor_kind="random", platform="cpu",
                                 multihost_processes=2)
    assert result.status == "COMPLETED", result.errors
    completed = [t for t in result.trials if t["status"] == "COMPLETED"]
    assert len(completed) == 2
    assert all(t["params_id"] for t in completed)

    # Both processes joined one jax.distributed cluster and saw the
    # 4-device global mesh (2 local x 2 processes).
    inits = list(events.read("multihost_init"))
    assert {e["process_id"] for e in inits} == {0, 1}
    assert all(e["process_count"] == 2 for e in inits)
    assert all(e["global_devices"] == 4 for e in inits)
    assert all(e["local_devices"] == 2 for e in inits)


def test_multihost_two_groups_do_not_cross_mirror(env):
    """Two 2-process groups on one sub-job: each follower must mirror
    ONLY its own leader's trials (a follower entering another group's
    collectives deadlocks the job — this test hanging is the failure
    mode)."""
    store, params, model = env
    job = store.create_train_job("mh2g", "IMAGE_CLASSIFICATION", None,
                                 TRAIN, VAL, {"MODEL_TRIAL_COUNT": 6})
    store.create_sub_train_job(job["id"], model["id"])
    sched = ProcessScheduler(store, params)
    result = sched.run_train_job(job["id"], n_workers=2, devices_per_trial=2,
                                 advisor_kind="random", platform="cpu",
                                 multihost_processes=2)
    assert result.status == "COMPLETED", result.errors
    completed = [t for t in result.trials if t["status"] == "COMPLETED"]
    assert len(completed) == 6
    inits = list(events.read("multihost_init"))
    assert len(inits) == 4  # 2 groups x 2 processes


def test_multihost_time_budget_terminates(env):
    """A TIME_HOURS-only budget (no trial count) must still terminate
    the whole group: the leader marks its service row stopped before
    exiting and the follower watches it — otherwise follower waits for
    a sub-job status the scheduler only writes after the follower
    itself exits (circular wait)."""
    store, params, model = env
    job = store.create_train_job("mhtime", "IMAGE_CLASSIFICATION", None,
                                 TRAIN, VAL, {"TIME_HOURS": 8.0 / 3600})
    store.create_sub_train_job(job["id"], model["id"])
    sched = ProcessScheduler(store, params)
    result = sched.run_train_job(job["id"], n_workers=1, devices_per_trial=2,
                                 advisor_kind="random", platform="cpu",
                                 multihost_processes=2)
    # Termination IS the assertion (the deadlock would hang this test);
    # trial count depends on how much of the 8s window startup ate.
    assert result.status == "COMPLETED", result.errors


def test_backend_init_watchdog_exits_structured(tmp_path):
    """A worker whose backend init hangs (dead TPU tunnel / unreachable
    coordinator) must exit with a structured error instead of stalling
    the scheduler's supervise loop forever (BENCH_r01's failure mode,
    worker edition)."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "RAFIKI_WORKER_DB": str(tmp_path / "meta.sqlite3"),
        "RAFIKI_WORKER_PARAMS_DIR": str(tmp_path / "params"),
        "RAFIKI_WORKER_SUB_JOB_ID": "nope",
        "RAFIKI_WORKER_ADVISOR_URL": "http://127.0.0.1:1",
        "RAFIKI_WORKER_ADVISOR_ID": "nope",
        # coordinator that will never answer -> distributed init blocks
        "RAFIKI_COORDINATOR_ADDRESS": "127.0.0.1:1",
        "RAFIKI_NUM_PROCESSES": "2",
        "RAFIKI_PROCESS_ID": "1",
        "RAFIKI_BACKEND_INIT_TIMEOUT_S": "3",
    })
    r = subprocess.run([sys.executable, "-m", "rafiki_tpu.worker.main"],
                       env=env, capture_output=True, text=True, timeout=120)
    assert r.returncode == 17
    assert "backend init exceeded" in r.stdout


def test_multihost_stop_event(env):
    """Stopping a multihost job terminates leader AND followers."""
    store, params, model = env
    job = store.create_train_job("mhstop", "IMAGE_CLASSIFICATION", None,
                                 TRAIN, VAL, {"MODEL_TRIAL_COUNT": 10_000})
    store.create_sub_train_job(job["id"], model["id"])
    sched = ProcessScheduler(store, params)
    stop = threading.Event()
    out = {}

    def run():
        out["result"] = sched.run_train_job(
            job["id"], n_workers=1, devices_per_trial=2,
            advisor_kind="random", platform="cpu",
            multihost_processes=2, stop_event=stop)

    th = threading.Thread(target=run, daemon=True)
    th.start()
    import time

    time.sleep(20)
    stop.set()
    th.join(timeout=90)
    assert not th.is_alive()
    assert out["result"].status == "STOPPED"


# ---------------------------------------------------------------------------
# Collective-init retry (worker/main.py initialize_collective): the
# flakiest moment of a multihost job gets bounded retries with backoff.
# Driven with a fake initialize fn — no real jax.distributed cluster.
# ---------------------------------------------------------------------------


def test_collective_init_retries_transient_failure(monkeypatch):
    from rafiki_tpu.worker.main import initialize_collective

    monkeypatch.setenv("RAFIKI_COLLECTIVE_INIT_RETRIES", "3")
    monkeypatch.setenv("RAFIKI_COLLECTIVE_INIT_BACKOFF_S", "0.01")
    calls = []

    def flaky(coordinator_address, num_processes, process_id):
        calls.append((coordinator_address, num_processes, process_id))
        if len(calls) == 1:
            raise RuntimeError("transient barrier race")

    initialize_collective(flaky, "127.0.0.1:9999", 2, 1)
    assert len(calls) == 2, "the failed attempt was not retried"
    assert calls[-1] == ("127.0.0.1:9999", 2, 1)


def test_collective_init_exhaustion_reraises(monkeypatch):
    from rafiki_tpu.worker.main import initialize_collective

    monkeypatch.setenv("RAFIKI_COLLECTIVE_INIT_RETRIES", "2")
    monkeypatch.setenv("RAFIKI_COLLECTIVE_INIT_BACKOFF_S", "0.01")
    calls = []

    def dead(coordinator_address, num_processes, process_id):
        calls.append(1)
        raise RuntimeError("coordinator unreachable")

    with pytest.raises(RuntimeError, match="coordinator unreachable"):
        initialize_collective(dead, "127.0.0.1:9999", 2, 0)
    assert len(calls) == 3, "retries + the final attempt"


def test_collective_init_chaos_fault_absorbed_by_retry(monkeypatch):
    """An injected collective.init error (the chaos site armed per
    attempt) must be absorbed exactly like a real init failure: the
    faulted attempt never reaches the initialize fn, the retry does."""
    from rafiki_tpu.chaos import FaultPlane, install, uninstall
    from rafiki_tpu.worker.main import initialize_collective

    monkeypatch.setenv("RAFIKI_COLLECTIVE_INIT_RETRIES", "3")
    monkeypatch.setenv("RAFIKI_COLLECTIVE_INIT_BACKOFF_S", "0.01")
    calls = []

    def ok(coordinator_address, num_processes, process_id):
        calls.append(1)

    install(FaultPlane.from_spec("seed=5;collective.init:error:times=1"))
    try:
        initialize_collective(ok, "127.0.0.1:9999", 2, 0)
    finally:
        uninstall()
    assert len(calls) == 1, "the injected-fault attempt leaked through"
