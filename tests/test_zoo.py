"""Every model family through the contract harness (small configs, CPU)."""

import numpy as np
import pytest

from rafiki_tpu.model.dev import test_model_class
from rafiki_tpu.models import MODEL_REGISTRY, get_model_class

IMG_TRAIN = "synthetic://images?classes=5&n=256&w=16&h=16&c=3&seed=0"
IMG_TEST = "synthetic://images?classes=5&n=128&w=16&h=16&c=3&seed=1"
POS_TRAIN = "synthetic://corpus?vocab=80&tags=6&n=128&len=12&seed=0"
POS_TEST = "synthetic://corpus?vocab=80&tags=6&n=64&len=12&seed=1"


def test_registry_resolves_all():
    for name in MODEL_REGISTRY:
        cls = get_model_class(name)
        assert isinstance(cls.get_knob_config(), dict)


def test_vgg_contract():
    from rafiki_tpu.models.vgg import Vgg

    score, preds = test_model_class(
        Vgg, "IMAGE_CLASSIFICATION",
        "synthetic://images?classes=5&n=512&w=16&h=16&c=3&seed=0", IMG_TEST,
        queries=[np.zeros((16, 16, 3), np.float32)],
        knobs=dict(depth=11, width_mult=0.25, dropout=0.1, learning_rate=1e-3,
                   batch_size=64, epochs=4, seed=0))
    assert score > 0.4
    assert len(preds[0]) == 5


def test_densenet_contract():
    from rafiki_tpu.models.densenet import DenseNet

    score, _ = test_model_class(
        DenseNet, "IMAGE_CLASSIFICATION", IMG_TRAIN, IMG_TEST,
        knobs=dict(depth=22, growth=12, learning_rate=3e-3, batch_size=64,
                   epochs=4, seed=0))
    assert score > 0.4


def test_skdt_contract():
    from rafiki_tpu.models.sk import SkDt

    score, preds = test_model_class(
        SkDt, "IMAGE_CLASSIFICATION", IMG_TRAIN, IMG_TEST,
        queries=[np.zeros((16, 16, 3), np.float32)],
        knobs=dict(max_depth=8, criterion="gini", seed=0))
    assert score > 0.3
    assert abs(sum(preds[0]) - 1.0) < 1e-6


def test_sksvm_contract():
    from rafiki_tpu.models.sk import SkSvm

    score, _ = test_model_class(
        SkSvm, "IMAGE_CLASSIFICATION", IMG_TRAIN, IMG_TEST,
        knobs=dict(C=1.0, kernel="linear", seed=0))
    assert score > 0.5


def test_pos_bilstm_contract():
    from rafiki_tpu.models.pos_bilstm import PosBiLstm

    score, preds = test_model_class(
        PosBiLstm, "POS_TAGGING", POS_TRAIN, POS_TEST,
        queries=[[5, 9, 3], [17, 2]],
        knobs=dict(embed_dim=32, hidden=32, learning_rate=5e-3, batch_size=32,
                   epochs=8, seed=0))
    assert score > 0.5  # token→tag mapping is learnable
    assert len(preds[0]) == 3 and len(preds[1]) == 2


def test_pos_hmm_contract():
    from rafiki_tpu.models.pos_hmm import PosBigramHmm

    score, preds = test_model_class(
        PosBigramHmm, "POS_TAGGING", POS_TRAIN, POS_TEST,
        queries=[[5, 9, 3]],
        knobs=dict(smoothing=0.1, seed=0))
    assert score > 0.5
    assert len(preds[0]) == 3
