"""Every model family through the contract harness (small configs, CPU)."""

import numpy as np
import pytest

from rafiki_tpu.model.dev import test_model_class
from rafiki_tpu.models import MODEL_REGISTRY, get_model_class

IMG_TRAIN = "synthetic://images?classes=5&n=256&w=16&h=16&c=3&seed=0"
IMG_TEST = "synthetic://images?classes=5&n=128&w=16&h=16&c=3&seed=1"
POS_TRAIN = "synthetic://corpus?vocab=80&tags=6&n=128&len=12&seed=0"
POS_TEST = "synthetic://corpus?vocab=80&tags=6&n=64&len=12&seed=1"


def test_registry_resolves_all():
    for name in MODEL_REGISTRY:
        cls = get_model_class(name)
        assert isinstance(cls.get_knob_config(), dict)


def test_vgg_contract():
    from rafiki_tpu.models.vgg import Vgg

    score, preds = test_model_class(
        Vgg, "IMAGE_CLASSIFICATION",
        "synthetic://images?classes=5&n=512&w=16&h=16&c=3&seed=0", IMG_TEST,
        queries=[np.zeros((16, 16, 3), np.float32)],
        knobs=dict(depth=11, width_mult=0.25, dropout=0.1, learning_rate=1e-3,
                   batch_size=64, epochs=4, seed=0))
    assert score > 0.4
    assert len(preds[0]) == 5


def test_densenet_contract():
    from rafiki_tpu.models.densenet import DenseNet

    score, _ = test_model_class(
        DenseNet, "IMAGE_CLASSIFICATION", IMG_TRAIN, IMG_TEST,
        knobs=dict(depth=22, growth=12, learning_rate=3e-3, batch_size=64,
                   epochs=4, seed=0))
    assert score > 0.4


def test_skdt_contract():
    from rafiki_tpu.models.sk import SkDt

    score, preds = test_model_class(
        SkDt, "IMAGE_CLASSIFICATION", IMG_TRAIN, IMG_TEST,
        queries=[np.zeros((16, 16, 3), np.float32)],
        knobs=dict(max_depth=8, criterion="gini", seed=0))
    assert score > 0.3
    assert abs(sum(preds[0]) - 1.0) < 1e-6


def test_sksvm_contract():
    from rafiki_tpu.models.sk import SkSvm

    score, _ = test_model_class(
        SkSvm, "IMAGE_CLASSIFICATION", IMG_TRAIN, IMG_TEST,
        knobs=dict(C=1.0, kernel="linear", seed=0))
    assert score > 0.5


def test_pos_bilstm_contract():
    from rafiki_tpu.models.pos_bilstm import PosBiLstm

    score, preds = test_model_class(
        PosBiLstm, "POS_TAGGING", POS_TRAIN, POS_TEST,
        queries=[[5, 9, 3], [17, 2]],
        knobs=dict(embed_dim=32, hidden=32, learning_rate=5e-3, batch_size=32,
                   epochs=8, seed=0))
    assert score > 0.5  # token→tag mapping is learnable
    assert len(preds[0]) == 3 and len(preds[1]) == 2


def test_transformer_contract():
    from rafiki_tpu.models.transformer import Transformer

    score, preds = test_model_class(
        Transformer, "TEXT_CLASSIFICATION",
        "synthetic://text?vocab=81&classes=5&n=512&len=16&seed=0",
        "synthetic://text?vocab=81&classes=5&n=128&len=16&seed=1",
        queries=[[5, 9, 3] * 5 + [1], [17, 2] * 8],
        knobs=dict(embed_dim=32, num_heads=2, num_layers=1,
                   learning_rate=5e-3, batch_size=32, epochs=3, seed=0))
    assert score > 0.5  # the signal token is learnable
    assert len(preds[0]) == 5  # one distribution over the 5 classes


def test_transformer_declares_a_shard_plan():
    # The zoo's sharded-lane citizen: its plan must solve (width 1 on
    # this small config without the pin) and honor the env pin — the
    # exact decision point the scheduler's lane fork reads.
    import os

    from rafiki_tpu.models.transformer import Transformer
    from rafiki_tpu.shard import ShardPlan

    m = Transformer(embed_dim=32, num_heads=2, num_layers=1,
                    learning_rate=5e-3, batch_size=32, epochs=1, seed=0)
    ds = m._prepared_dataset(
        "synthetic://text?vocab=81&classes=5&n=64&len=16&seed=0")
    prev = os.environ.pop("RAFIKI_SHARD_WIDTH", None)
    try:
        plan = m.shard_plan(ds)
        assert isinstance(plan, ShardPlan)
        assert plan.width == 1 and plan.hbm_bytes > 0
        os.environ["RAFIKI_SHARD_WIDTH"] = "2"
        assert m.shard_plan(ds).width == 2
    finally:
        if prev is None:
            os.environ.pop("RAFIKI_SHARD_WIDTH", None)
        else:
            os.environ["RAFIKI_SHARD_WIDTH"] = prev


def test_pos_hmm_contract():
    from rafiki_tpu.models.pos_hmm import PosBigramHmm

    score, preds = test_model_class(
        PosBigramHmm, "POS_TAGGING", POS_TRAIN, POS_TEST,
        queries=[[5, 9, 3]],
        knobs=dict(smoothing=0.1, seed=0))
    assert score > 0.5
    assert len(preds[0]) == 3
