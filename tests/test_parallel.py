"""Mesh partitioning, within-trial data parallelism, stacked ensembles —
on the fake 8-chip CPU pod."""

import jax
import numpy as np
import pytest

from rafiki_tpu.parallel.mesh import data_parallel_mesh, local_devices, partition_devices


def test_eight_fake_devices():
    assert len(local_devices()) == 8


def test_partition_devices():
    devs = local_devices()
    parts = partition_devices(devs, 4)
    assert len(parts) == 4 and all(len(p) == 2 for p in parts)
    with pytest.raises(ValueError):
        partition_devices(devs, 3)


def test_dp_training_matches_single_device():
    """A dp-sharded trial must learn as well as a single-device trial
    (same model, same data; gradient all-reduce from shardings)."""
    from rafiki_tpu.models.ff import FeedForward

    TRAIN = "synthetic://images?classes=5&n=512&w=8&h=8&seed=0"
    VAL = "synthetic://images?classes=5&n=128&w=8&h=8&seed=1"
    knobs = dict(hidden_layers=1, hidden_units=64, learning_rate=3e-3,
                 batch_size=64, epochs=3, seed=0)

    single = FeedForward(**knobs)
    single.train(TRAIN)
    s1 = single.evaluate(VAL)

    dp = FeedForward(**knobs)
    dp.set_mesh(data_parallel_mesh(local_devices()[:4]))
    dp.train(TRAIN)
    s4 = dp.evaluate(VAL)

    assert s1 > 0.8 and s4 > 0.8
    assert abs(s1 - s4) < 0.1


def test_dp_batch_actually_sharded():
    """The compiled input sharding must split the batch over 'dp'."""
    from rafiki_tpu.ops.train import _ShardingPlan

    mesh = data_parallel_mesh(local_devices()[:4])
    plan = _ShardingPlan.build(mesh)
    batch = plan.put_batch({"x": np.zeros((64, 8), np.float32)})
    shard_shapes = {s.data.shape for s in batch["x"].addressable_shards}
    assert shard_shapes == {(16, 8)}


def test_stacked_ensemble_matches_individual():
    from rafiki_tpu.parallel.ensemble import StackedEnsemble
    from rafiki_tpu.models.ff import FeedForward

    TRAIN = "synthetic://images?classes=5&n=256&w=8&h=8&seed=0"
    knobs = dict(hidden_layers=1, hidden_units=32, learning_rate=3e-3,
                 batch_size=64, epochs=1)
    models = []
    for seed in (0, 1):
        m = FeedForward(**knobs, seed=0)
        m._seed = seed
        m.train(TRAIN)
        models.append(m)

    x = np.random.default_rng(0).uniform(0, 1, size=(16, 8, 8, 1)).astype(np.float32)
    indiv = np.stack([m.predict_proba(x) for m in models])

    apply_fn = models[0]._loop.apply_fn
    ens = StackedEnsemble(lambda p, b: apply_fn(p, b),
                          [m._loop.params for m in models],
                          devices=local_devices()[:2])
    stacked = ens.predict_proba({"x": x})
    assert stacked.shape == (2, 16, 5)
    np.testing.assert_allclose(stacked, indiv, atol=2e-2)  # bf16 tolerance
    np.testing.assert_allclose(ens.ensemble_proba({"x": x}), indiv.mean(0), atol=2e-2)


def test_stacked_ensemble_sharded_over_model_axis():
    from rafiki_tpu.parallel.ensemble import StackedEnsemble
    import flax.linen as nn
    import jax.numpy as jnp

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(3)(x.reshape((x.shape[0], -1)))

    mod = Tiny()
    params = [mod.init(jax.random.PRNGKey(i), jnp.zeros((1, 4)))["params"]
              for i in range(4)]
    ens = StackedEnsemble(lambda p, b: mod.apply({"params": p}, b["x"]),
                          params, devices=local_devices()[:4])
    assert ens.mesh is not None
    out = ens.predict_proba({"x": np.zeros((8, 4), np.float32)})
    assert out.shape == (4, 8, 3)
    np.testing.assert_allclose(out.sum(-1), 1.0, atol=1e-5)


def test_build_stacked_fallback_reasons():
    from rafiki_tpu.parallel.serving import build_stacked

    class _NotJax:
        pass

    got = build_stacked([{"model_name": "ff"}], [_NotJax()])
    assert got == (None, "single-trial")
    got = build_stacked([{"model_name": "ff"}, {"model_name": "cnn"}],
                        [_NotJax(), _NotJax()])
    assert got == (None, "mixed-templates")
    got = build_stacked([{"model_name": "ff"}, {"model_name": "ff"}],
                        [_NotJax(), _NotJax()])
    assert got == (None, "not-jax-loaded")


def test_stacked_serving_bit_parity_with_serial_ensemble():
    """The acceptance contract of docs/serving.md: on CPU the stacked
    route's predictions BIT-MATCH the host-side ensemble of k serial
    forwards — same float32 mean + renormalize op sequence, so the
    route choice is invisible to callers."""
    from rafiki_tpu.models.ff import FeedForward
    from rafiki_tpu.parallel.serving import build_stacked
    from rafiki_tpu.predictor.ensemble import ensemble_predictions

    TRAIN = "synthetic://images?classes=5&n=256&w=8&h=8&seed=0"
    knobs = dict(hidden_layers=1, hidden_units=32, learning_rate=3e-3,
                 batch_size=64, epochs=1)
    trials, models = [], []
    for seed in (0, 1, 2):
        m = FeedForward(**knobs, seed=0)
        m._seed = seed
        m.train(TRAIN)
        models.append(m)
        trials.append({"model_name": "ff"})

    rng = np.random.default_rng(7)
    queries = rng.uniform(0, 1, size=(5, 8, 8, 1)).astype(np.float32).tolist()

    # Serial route FIRST: building the stacked adapter hands the param
    # copies to the fused program and destroys models[1:].
    serial = [m.predict(queries) for m in models]
    host = [ensemble_predictions([s[i] for s in serial])
            for i in range(len(queries))]

    stacked, reason = build_stacked(trials, models, batch_size=8)
    assert reason == "stacked" and stacked is not None
    assert stacked.warmup() > 0.0
    fused = stacked.predict(queries)
    assert np.array_equal(np.asarray(fused, dtype=np.float64),
                          np.asarray(host, dtype=np.float64))
    stacked.destroy()
