"""REST integration: real HTTP server + Client SDK, full user journey.

This is the rebuild's analog of the reference's quickstart scripts
(SURVEY.md §4 "quickstart scripts as integration tests") — but runnable
under pytest against the fake 8-chip CPU pod.
"""

import threading

import numpy as np
import pytest
from werkzeug.serving import make_server

from rafiki_tpu.admin import Admin
from rafiki_tpu.admin.app import AdminApp
from rafiki_tpu.client import Client, ClientError

from tests.test_admin import FF_SOURCE, TRAIN, VAL


@pytest.fixture()
def server(tmp_config):
    admin = Admin(config=tmp_config)
    app = AdminApp(admin)
    srv = make_server("127.0.0.1", 0, app, threaded=True)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    yield srv.server_port
    srv.shutdown()
    thread.join(timeout=10)
    admin.stop()


@pytest.fixture()
def superadmin(server, tmp_config):
    c = Client(admin_port=server)
    c.login(tmp_config.superadmin_email, tmp_config.superadmin_password)
    return c


def test_login_and_auth_required(server, tmp_config):
    c = Client(admin_port=server)
    with pytest.raises(ClientError) as e:
        c.get_models()
    assert e.value.status == 401
    with pytest.raises(ClientError) as e:
        c.login(tmp_config.superadmin_email, "wrong")
    assert e.value.status == 401
    out = c.login(tmp_config.superadmin_email, tmp_config.superadmin_password)
    assert out["user_type"] == "SUPERADMIN"
    assert c.get_models() == []


def test_role_enforcement(server, superadmin):
    superadmin.create_user("app@x", "pw", "APP_DEVELOPER")
    c = Client(admin_port=server)
    c.login("app@x", "pw")
    with pytest.raises(ClientError) as e:
        c.create_user("other@x", "pw", "APP_DEVELOPER")  # app dev can't mint users
    assert e.value.status == 401
    with pytest.raises(ClientError) as e:
        c.get_users()
    assert e.value.status == 401


def test_rest_full_journey(server, superadmin, tmp_path):
    """create users → upload model (multipart) → train job → poll →
    best trials → logs → inference job → predict over HTTP → stop."""
    superadmin.create_user("modeldev@x", "pw", "MODEL_DEVELOPER")
    superadmin.create_user("appdev@x", "pw", "APP_DEVELOPER")

    dev = Client(admin_port=server)
    dev.login("modeldev@x", "pw")
    model_path = tmp_path / "tinyff.py"
    model_path.write_bytes(FF_SOURCE)
    m = dev.create_model("tinyff", "IMAGE_CLASSIFICATION", model_path, "TinyFF")
    assert m["name"] == "tinyff"
    assert dev.download_model_file("tinyff") == FF_SOURCE

    appdev = Client(admin_port=server)
    appdev.login("appdev@x", "pw")
    job = appdev.create_train_job(
        "restapp", "IMAGE_CLASSIFICATION", TRAIN, VAL,
        {"MODEL_TRIAL_COUNT": 3}, advisor_kind="random")
    assert job["status"] == "STARTED"

    done = appdev.wait_until_train_job_has_stopped("restapp", timeout=300,
                                                   poll_s=0.5)
    assert done["status"] == "COMPLETED"
    trials = appdev.get_trials_of_train_job("restapp")
    assert len(trials) == 3
    best = appdev.get_best_trials_of_train_job("restapp", max_count=2)
    assert best and best[0]["score"] is not None
    assert isinstance(appdev.get_trial_logs(best[0]["id"]), list)
    assert len(appdev.get_trial_parameters(best[0]["id"])) > 100

    inf = appdev.create_inference_job("restapp")
    queries = np.random.default_rng(0).uniform(0, 1, size=(2, 8, 8, 1)).tolist()
    preds = appdev.predict("restapp", queries)
    assert len(preds) == 2 and abs(sum(preds[0]) - 1.0) < 1e-3

    # the published predictor endpoint (reference: per-job predictor port)
    assert inf["predictor_host"]
    direct = appdev.predict_via_predictor(inf["predictor_host"], queries)
    assert np.allclose(direct, preds, atol=1e-6)

    appdev.stop_inference_job("restapp")
    with pytest.raises(ClientError) as e:
        appdev.get_inference_job("restapp")
    assert e.value.status == 404


def test_private_model_file_access(server, superadmin, tmp_path):
    superadmin.create_user("owner@x", "pw", "MODEL_DEVELOPER")
    superadmin.create_user("other@x", "pw", "MODEL_DEVELOPER")
    owner = Client(admin_port=server)
    owner.login("owner@x", "pw")
    path = tmp_path / "m.py"
    path.write_bytes(FF_SOURCE)
    owner.create_model("privm", "IMAGE_CLASSIFICATION", path, "TinyFF",
                       access_right="PRIVATE")
    assert owner.download_model_file("privm") == FF_SOURCE     # owner OK
    assert superadmin.download_model_file("privm") == FF_SOURCE  # admin OK
    other = Client(admin_port=server)
    other.login("other@x", "pw")
    with pytest.raises(ClientError) as e:
        other.download_model_file("privm")                     # stranger blocked
    assert e.value.status == 401


def test_missing_field_is_400(server, superadmin):
    with pytest.raises(ClientError) as e:
        superadmin._post("/users", {"email": "nopw@x"})  # no password/user_type
    assert e.value.status == 400
    assert "password" in e.value.message


def test_stop_scoped_to_owner(server, superadmin):
    """An app developer cannot stop another developer's train job."""
    superadmin.create_user("dev1@x", "pw", "MODEL_DEVELOPER")
    superadmin.create_user("a1@x", "pw", "APP_DEVELOPER")
    superadmin.create_user("a2@x", "pw", "APP_DEVELOPER")
    import tempfile
    from pathlib import Path
    dev = Client(admin_port=server)
    dev.login("dev1@x", "pw")
    with tempfile.TemporaryDirectory() as d:
        p = Path(d) / "m.py"
        p.write_bytes(FF_SOURCE)
        dev.create_model("scopeff", "IMAGE_CLASSIFICATION", p, "TinyFF")
    a1 = Client(admin_port=server)
    a1.login("a1@x", "pw")
    a1.create_train_job("scopedapp", "IMAGE_CLASSIFICATION", TRAIN, VAL,
                        {"MODEL_TRIAL_COUNT": 20}, advisor_kind="random")
    a2 = Client(admin_port=server)
    a2.login("a2@x", "pw")
    with pytest.raises(ClientError) as e:
        a2.stop_train_job("scopedapp")  # not a2's job → 404, still running
    assert e.value.status == 404
    out = a1.stop_train_job("scopedapp")
    assert out["status"] in ("STOPPED", "COMPLETED", "RUNNING", "STARTED")
    a1.wait_until_train_job_has_stopped("scopedapp", timeout=120, poll_s=0.5)


def test_web_ui_served(server):
    import requests

    resp = requests.get(f"http://127.0.0.1:{server}/")
    assert resp.status_code == 200
    assert "text/html" in resp.headers["Content-Type"]
    assert "rafiki-tpu" in resp.text and "login-form" in resp.text
    # the parity surfaces: per-trial metric plots (define_plot channel),
    # trial-log viewer, stop controls for train + inference jobs, and
    # the full browser journey: model upload, train-job creation, user
    # create/ban (every client verb is browser-drivable)
    for marker in ("renderTrial", "linePlot", "Trial log", "stop-job",
                   "stop-inf", "new-model", "new-job", "new-user",
                   'class="ghost ban"', "</html>"):
        assert marker in resp.text, f"web UI missing {marker!r}"
    # balanced script block (a truncated inline script serves silently)
    assert resp.text.count("<script>") == resp.text.count("</script>") == 1


def test_web_ui_form_calls(server, superadmin, tmp_config):
    """The exact REST calls the web UI forms issue (JSON bodies, not
    the SDK's multipart): upload a model template, create a train job,
    create and ban a user."""
    import requests

    base = f"http://127.0.0.1:{server}"
    tok = requests.post(f"{base}/tokens", json={
        "email": tmp_config.superadmin_email,
        "password": tmp_config.superadmin_password}).json()["token"]
    h = {"Authorization": f"Bearer {tok}"}

    r = requests.post(f"{base}/models", headers=h, json={
        "name": "ui-upload", "task": "IMAGE_CLASSIFICATION",
        "model_class": "TinyFF", "model_file": FF_SOURCE.decode(),
        "access_right": "PRIVATE"})
    assert r.status_code == 201, r.text
    assert any(m["name"] == "ui-upload" for m in
               requests.get(f"{base}/models", headers=h).json())

    r = requests.post(f"{base}/train_jobs", headers=h, json={
        "app": "ui-app", "task": "IMAGE_CLASSIFICATION",
        "train_dataset_uri": TRAIN, "val_dataset_uri": VAL,
        "budget": {"MODEL_TRIAL_COUNT": 1}, "advisor_kind": "random"})
    assert r.status_code == 201, r.text
    superadmin.wait_until_train_job_has_stopped("ui-app", timeout=180,
                                                poll_s=0.5)

    r = requests.post(f"{base}/users", headers=h, json={
        "email": "banme@x.y", "password": "pw", "user_type": "APP_DEVELOPER"})
    assert r.status_code in (200, 201), r.text
    r = requests.delete(f"{base}/users", headers=h, json={"email": "banme@x.y"})
    assert r.status_code == 200, r.text
    users = requests.get(f"{base}/users", headers=h).json()
    assert next(u for u in users if u["email"] == "banme@x.y")["banned"]


def test_404s(server, superadmin):
    with pytest.raises(ClientError) as e:
        superadmin.get_model("ghost")
    assert e.value.status == 404
    with pytest.raises(ClientError) as e:
        superadmin.get_train_job("ghost")
    assert e.value.status == 404
