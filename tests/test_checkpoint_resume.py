"""Mid-trial checkpointing and trial resume (capability the reference lacks)."""

import pytest

from rafiki_tpu.advisor import AdvisorService
from rafiki_tpu.model.base import load_model_class
from rafiki_tpu.store import MetaStore, ParamsStore
from rafiki_tpu.worker.train import InProcAdvisorHandle, TrainWorker

FF3_SOURCE = b"""
from rafiki_tpu.model.base import JaxModel
from rafiki_tpu.model.knobs import FixedKnob, FloatKnob

class FF3(JaxModel):
    @staticmethod
    def get_knob_config():
        return {
            "learning_rate": FloatKnob(1e-3, 1e-2, is_exp=True),
            "batch_size": FixedKnob(32),
            "epochs": FixedKnob(3),
        }

    def build_module(self, num_classes, input_shape):
        from rafiki_tpu.models.ff import _Mlp
        return _Mlp(hidden_layers=1, hidden_units=16, num_classes=num_classes)
"""

TRAIN = "synthetic://images?classes=5&n=256&w=8&h=8&seed=0"
VAL = "synthetic://images?classes=5&n=128&w=8&h=8&seed=1"


@pytest.fixture()
def env(tmp_path):
    store = MetaStore(tmp_path / "meta.sqlite3")
    params = ParamsStore(tmp_path / "params")
    row = store.create_model("ff3", "IMAGE_CLASSIFICATION", None, FF3_SOURCE, "FF3")
    job = store.create_train_job("ckptapp", "IMAGE_CLASSIFICATION", None,
                                 TRAIN, VAL, {"MODEL_TRIAL_COUNT": 1})
    sub = store.create_sub_train_job(job["id"], row["id"])
    cls = load_model_class(row["model_file"], "FF3")
    advisors = AdvisorService()
    aid = advisors.create_advisor(cls.get_knob_config(), kind="random")
    return store, params, sub, cls, InProcAdvisorHandle(advisors, aid)


def _worker(store, params, sub, cls, advisor, **kw):
    return TrainWorker(store, params, sub["id"], cls, advisor, TRAIN, VAL,
                       {"MODEL_TRIAL_COUNT": 1}, async_persist=False, **kw)


def test_checkpoints_written_and_cleaned(env):
    store, params, sub, cls, advisor = env
    w = _worker(store, params, sub, cls, advisor, checkpoint_every=1)
    w.run()
    t = store.get_trials_of_sub_train_job(sub["id"])[0]
    assert t["status"] == "COMPLETED"
    # checkpoints were superseded by the final params and deleted
    assert params.latest_checkpoint(t["id"]) is None
    assert t["params_id"] in params.list()


def test_checkpoint_roundtrip_exact(env):
    """dump_checkpoint/restore_checkpoint resume training mid-trial with
    full optimizer state: a 1+2-epoch split run equals a 3-epoch run."""
    store, params, sub, cls, advisor = env
    knobs = {"learning_rate": 3e-3, "batch_size": 32, "epochs": 3}

    blobs = {}
    m1 = cls(**knobs)
    m1.set_checkpoint_sink(lambda epoch, mk: blobs.__setitem__(epoch, mk()))
    m1.train(TRAIN)
    full_score = m1.evaluate(VAL)
    full_params = m1.dump_parameters()
    m1.destroy()

    # restore from the epoch-0 snapshot, train the remaining 2 epochs
    m2 = cls(**knobs)
    start = m2.restore_checkpoint(blobs[0])
    assert start == 1
    m2.train(TRAIN)
    split_score = m2.evaluate(VAL)
    split_params = m2.dump_parameters()
    m2.destroy()

    assert abs(split_score - full_score) < 1e-6
    assert split_params == full_params  # bitwise identical resume


def test_resume_trial_after_crash(env):
    """A trial interrupted after 1 of 3 epochs resumes from its
    checkpoint and completes."""
    store, params, sub, cls, advisor = env
    w = _worker(store, params, sub, cls, advisor, checkpoint_every=1)
    knobs = {"learning_rate": 3e-3, "batch_size": 32, "epochs": 3}

    # Simulate a crash: run the trial but make evaluate blow up after
    # checkpoints exist.
    class Crashy(cls):  # type: ignore[misc, valid-type]
        def evaluate(self, uri):
            raise RuntimeError("simulated worker crash")

    Crashy.__name__ = cls.__name__
    w_crash = TrainWorker(store, params, sub["id"], Crashy, advisor, TRAIN, VAL,
                          {"MODEL_TRIAL_COUNT": 1}, async_persist=False,
                          checkpoint_every=1)
    t = w_crash.run_trial(knobs)
    assert t["status"] == "ERRORED"
    assert params.latest_checkpoint(t["id"]) is not None  # progress survived

    # A healthy worker adopts and resumes the trial.
    out = w.resume_trial(t["id"])
    assert out["status"] == "COMPLETED"
    assert out["error"] is None  # stale crash traceback cleared
    assert out["score"] is not None
    assert params.latest_checkpoint(t["id"]) is None  # cleaned up


def test_resume_with_async_persist_reports_final_status(env):
    """resume_trial drains the saver: callers see the terminal status,
    not a mid-persist snapshot — even on a worker whose saver was
    already closed by a previous run()."""
    store, params, sub, cls, advisor = env
    knobs = {"learning_rate": 3e-3, "batch_size": 32, "epochs": 3}

    class Crashy(cls):  # type: ignore[misc, valid-type]
        def evaluate(self, uri):
            raise RuntimeError("boom")

    Crashy.__name__ = cls.__name__
    w_crash = TrainWorker(store, params, sub["id"], Crashy, advisor, TRAIN, VAL,
                          {"MODEL_TRIAL_COUNT": 1}, async_persist=False,
                          checkpoint_every=1)
    t = w_crash.run_trial(knobs)

    w = TrainWorker(store, params, sub["id"], cls, advisor, TRAIN, VAL,
                    {"MODEL_TRIAL_COUNT": 1}, async_persist=True,
                    checkpoint_every=1)
    w.run()  # closes the saver thread...
    out = w.resume_trial(t["id"])  # ...which must restart for this
    assert out["status"] == "COMPLETED"
    assert out["params_id"] and len(params.load(out["params_id"])) > 100
