"""Tier-1 enforcement of the static-analysis pass: the repo must
analyze CLEAN — zero unsuppressed findings over the same path set the
CLI and scripts/check_lint.sh use. A new violation of any encoded
failure class (docs/static_analysis.md) fails the suite exactly like a
broken test."""

import os

from rafiki_tpu.analysis import analyze_paths, load_builtin_checkers

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT_PATHS = [os.path.join(REPO, "rafiki_tpu"),
              os.path.join(REPO, "bench.py"),
              os.path.join(REPO, "scripts")]

load_builtin_checkers()


def test_repo_analyzes_clean():
    result = analyze_paths(LINT_PATHS)
    assert result.parse_errors == []
    assert result.files_analyzed > 50  # the walk actually saw the tree
    pretty = [f"{f.location()} {f.checker_id}: {f.message}"
              for f in result.unsuppressed]
    assert pretty == [], "\n".join(pretty)


def test_every_suppression_is_justified():
    result = analyze_paths(LINT_PATHS)
    for f in result.findings:
        if f.suppressed:
            assert f.justification, f"{f.location()} suppressed without why"


def test_repo_clean_under_contract_checkers():
    """RF014–RF016 specifically: every journal kind written is read (or
    justify-suppressed), every read field is written, every knob agrees
    on its default and reaches its spawned children."""
    result = analyze_paths(LINT_PATHS, select=["RF014", "RF015", "RF016"])
    pretty = [f"{f.location()} {f.checker_id}: {f.message}"
              for f in result.unsuppressed]
    assert pretty == [], "\n".join(pretty)


def test_repo_clean_under_full_gather_checker():
    """RF019 specifically (docs/sharding.md): group-sharded train
    state is materialized on a host ONLY through shard/checkpoint.py's
    manifest path (save_sharded / gather_state)."""
    result = analyze_paths(LINT_PATHS, select=["RF019"])
    pretty = [f"{f.location()} {f.checker_id}: {f.message}"
              for f in result.unsuppressed]
    assert pretty == [], "\n".join(pretty)


def test_contracts_manifest_golden_matches_tree():
    """The committed manifest is byte-identical to a fresh extraction —
    the in-process form of check_lint.sh's contracts diff. On drift:
    python -m rafiki_tpu.analysis --contracts > tests/data/contracts_manifest.json
    """
    from rafiki_tpu.analysis.contracts.manifest import (
        dump_manifest, manifest_for_paths)
    fresh = dump_manifest(manifest_for_paths(LINT_PATHS, root=REPO))
    golden = open(os.path.join(
        REPO, "tests/data/contracts_manifest.json")).read()
    assert fresh == golden


def test_knob_docs_golden_matches_tree():
    """docs/knobs.md is generated; regenerate on drift:
    python -m rafiki_tpu.analysis --contracts --docs > docs/knobs.md
    """
    from rafiki_tpu.analysis.contracts.envknobs import extract_env
    from rafiki_tpu.analysis.contracts.knobdocs import generate_knobs_md
    from rafiki_tpu.analysis.contracts.manifest import _load_modules
    fresh = generate_knobs_md(extract_env(_load_modules(LINT_PATHS,
                                                        root=REPO)))
    golden = open(os.path.join(REPO, "docs/knobs.md")).read()
    assert fresh == golden
    assert "undocumented" not in fresh
