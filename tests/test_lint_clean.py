"""Tier-1 enforcement of the static-analysis pass: the repo must
analyze CLEAN — zero unsuppressed findings over the same path set the
CLI and scripts/check_lint.sh use. A new violation of any encoded
failure class (docs/static_analysis.md) fails the suite exactly like a
broken test."""

import os

from rafiki_tpu.analysis import analyze_paths, load_builtin_checkers

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT_PATHS = [os.path.join(REPO, "rafiki_tpu"),
              os.path.join(REPO, "bench.py"),
              os.path.join(REPO, "scripts")]

load_builtin_checkers()


def test_repo_analyzes_clean():
    result = analyze_paths(LINT_PATHS)
    assert result.parse_errors == []
    assert result.files_analyzed > 50  # the walk actually saw the tree
    pretty = [f"{f.location()} {f.checker_id}: {f.message}"
              for f in result.unsuppressed]
    assert pretty == [], "\n".join(pretty)


def test_every_suppression_is_justified():
    result = analyze_paths(LINT_PATHS)
    for f in result.findings:
        if f.suppressed:
            assert f.justification, f"{f.location()} suppressed without why"
