"""Multi-tenant serving fabric (docs/multitenancy.md): QoS directory,
weighted-fair admission with per-tenant quotas, bounded accounting,
HBM-budgeted program residency, co-hosted multi-model workers, the
twin's per-tenant validation, and the job-admission arbiter.

The end-to-end isolation proof (victim p99 inside its budget under an
aggressor flood, from per-tenant journals alone) lives in the
``noisy-neighbor-shed`` chaos scenario gated by
scripts/tenancy_smoke.py in BOTH polarities; these tests pin the unit
semantics each layer contributes to that gate.
"""

import json
import threading
import time

import pytest

from rafiki_tpu import telemetry
from rafiki_tpu.obs import journal as journal_mod
from rafiki_tpu.obs.journal import journal
from rafiki_tpu.tenancy import (
    ANON_TENANT, BoundedTenantMap, ProgramHost, ProgramSpec,
    ResidencyManager, TenantAccounting, TenantAdmissionController,
    TenantDirectory, TIERS, wrap_query)
from rafiki_tpu.tenancy.arbiter import (
    JobAdmissionGate, JobRejected, ModelUnvalidated)


@pytest.fixture
def journaled(tmp_path):
    journal.configure(tmp_path, role="test")
    try:
        yield tmp_path
    finally:
        journal.close()


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    yield
    telemetry.reset()


def _directory(**kw):
    kw.setdefault("tiers", {"alice": "gold", "bob": "batch"})
    return TenantDirectory(**kw)


# -- qos -------------------------------------------------------------------


def test_directory_resolves_tiers_and_defaults():
    d = _directory(default_tier="std")
    assert d.tier_of("alice").name == "gold"
    assert d.tier_of("bob").name == "batch"
    assert d.tier_of("stranger").name == "std"
    assert d.tier_of(None).name == "std"
    tiers = TIERS()
    assert tiers["gold"].weight > tiers["std"].weight > tiers["batch"].weight
    assert tiers["gold"].p99_budget_ms < tiers["batch"].p99_budget_ms


def test_unweighted_knob_flattens_weights(monkeypatch):
    monkeypatch.setenv("RAFIKI_TENANT_UNWEIGHTED", "1")
    tiers = TIERS()
    assert tiers["gold"].weight == tiers["batch"].weight == 1.0
    d = _directory()
    assert d.unweighted and d.quota_frac == 1.0


# -- admission -------------------------------------------------------------


def test_quota_shed_charged_to_the_flooder():
    """A tenant beyond its queue quota sheds with ``tenant_quota``
    while the other tenant still admits — the noisy-neighbor core."""
    from rafiki_tpu.gateway.admission import ShedError

    ctl = TenantAdmissionController(_directory(quota_frac=0.5),
                                    max_inflight=2, max_queue=4)
    deadline = time.monotonic() + 5.0
    # bob fills his inflight quota (1 of 2 slots) ...
    ctl.admit(deadline, tenant="bob")
    # ... then his queue quota (ceil(4*0.5) = 2 waiters).
    waits = []
    started = threading.Barrier(3)

    def waiter():
        started.wait()
        waits.append(ctl.admit(time.monotonic() + 5.0, tenant="bob"))

    ths = [threading.Thread(target=waiter, daemon=True) for _ in range(2)]
    for th in ths:
        th.start()
    started.wait()
    deadline2 = time.monotonic() + 2.0
    while ctl.tenant_waiting("bob") < 2:
        assert time.monotonic() < deadline2, "waiters never queued"
        time.sleep(0.005)
    with pytest.raises(ShedError) as ei:
        ctl.admit(time.monotonic() + 5.0, tenant="bob")
    assert ei.value.reason == "tenant_quota"
    # alice is untouched by bob's quota exhaustion: she rides the
    # shared queue straight through (her own quota is empty).
    ctl.admit(time.monotonic() + 5.0, tenant="alice")
    assert ctl.tenant_inflight("alice") == 1
    ctl.release(tenant="alice")
    # bob's inflight quota is ONE slot, so his waiters drain strictly
    # one release at a time.
    deadline3 = time.monotonic() + 5.0
    for want in (1, 2):
        ctl.release(tenant="bob")
        while len(waits) < want:
            assert time.monotonic() < deadline3, "waiter never admitted"
            time.sleep(0.005)
    ctl.release(tenant="bob")
    for th in ths:
        th.join(timeout=5.0)
    assert len(waits) == 2


def test_weighted_grant_prefers_lower_charge_per_weight():
    """With one slot freed and both tenants waiting at equal inflight,
    the gold tenant (weight 4) is chosen over batch (weight 1) —
    inflight/weight charge, not FIFO age, decides."""
    ctl = TenantAdmissionController(_directory(quota_frac=1.0),
                                    max_inflight=2, max_queue=8)
    ctl.admit(time.monotonic() + 5.0, tenant="alice")
    ctl.admit(time.monotonic() + 5.0, tenant="bob")
    order = []
    started = threading.Barrier(3)

    def waiter(tenant):
        started.wait()
        ctl.admit(time.monotonic() + 5.0, tenant=tenant)
        order.append(tenant)

    # bob queues FIRST: under FIFO he'd win the freed slot.
    tb = threading.Thread(target=waiter, args=("bob",), daemon=True)
    ta = threading.Thread(target=waiter, args=("alice",), daemon=True)
    tb.start(), ta.start()
    started.wait()
    deadline = time.monotonic() + 2.0
    while ctl.tenant_waiting("alice") + ctl.tenant_waiting("bob") < 2:
        assert time.monotonic() < deadline, "waiters never queued"
        time.sleep(0.005)
    # Free alice's slot: both tenants now at inflight 0 vs 1... alice
    # charge 0/4, bob would be 1/1 — alice must be chosen even though
    # bob waited longer.
    ctl.release(tenant="alice")
    ta.join(timeout=5.0)
    assert order == ["alice"]
    ctl.release(tenant="bob")
    tb.join(timeout=5.0)
    assert sorted(order) == ["alice", "bob"]
    ctl.release(tenant="alice"), ctl.release(tenant="bob")


def test_admission_state_stays_bounded():
    d = _directory(tiers={}, max_tenants=8)
    ctl = TenantAdmissionController(d, max_inflight=4, max_queue=4)
    for i in range(100):
        t = f"rotating-{i}"
        ctl.admit(time.monotonic() + 1.0, tenant=t)
        ctl.release(tenant=t)
    assert len(ctl._slots) <= 8


# -- accounting ------------------------------------------------------------


def test_bounded_tenant_map_evicts_lru():
    m = BoundedTenantMap(cap=3, factory=dict)
    for t in ("a", "b", "c"):
        m.get(t)
    m.get("a")                      # refresh a's recency
    m.get("d")                      # evicts b (LRU), not a
    assert "a" in m and "d" in m and "b" not in m
    assert len(m) == 3
    assert telemetry.get_counter("tenant.accounting_evictions") == 1


def test_accounting_burn_and_summary_flush(journaled):
    acc = TenantAccounting(_directory())
    for _ in range(20):
        acc.admitted("alice", waited_s=0.0)
        acc.completed("alice", e2e_s=0.01, ok=True)    # 10ms ≪ 200ms gold
    acc.shed("bob", "tenant_quota")
    assert acc.burn("alice") < 1.0
    per = acc.per_tenant()
    assert per["alice"]["admitted"] == 20
    assert per["bob"]["shed"] == 1
    acc.flush()
    journal.close()
    recs = journal_mod.read_dir(journaled)
    summaries = [r for r in recs if r.get("kind") == "tenant"
                 and r.get("name") == "summary"]
    assert summaries and summaries[-1]["tenants"]["alice"]["admitted"] == 20
    sheds = [r for r in recs if r.get("kind") == "tenant"
             and r.get("name") == "shed"]
    assert [r["tenant"] for r in sheds] == ["bob"]


# -- residency + hosting ---------------------------------------------------


class _TagModel:
    def __init__(self, tag):
        self.tag = tag
        self.destroyed = False

    def predict(self, queries):
        return [f"{self.tag}:{q}" for q in queries]

    def destroy(self):
        self.destroyed = True


def test_residency_lru_swap_journaled(journaled):
    rm = ResidencyManager(budget_bytes=100)
    a, b = _TagModel("A"), _TagModel("B")
    assert rm.activate("jobA", 80, lambda: a) is a
    assert rm.activate("jobA", 80, lambda: a) is a          # hit
    assert rm.activate("jobB", 80, lambda: b) is b          # evicts A
    assert a.destroyed and not b.destroyed
    assert rm.used_bytes() <= 100
    with pytest.raises(MemoryError):
        rm.activate("huge", 101, lambda: _TagModel("X"))
    journal.close()
    events = [r["event"] for r in journal_mod.read_dir(journaled)
              if r.get("kind") == "tenancy" and r.get("name") == "residency"]
    assert events == ["activate", "hit", "evict", "activate"]


def test_program_host_routes_by_program_tag(journaled):
    host = ProgramHost([
        ProgramSpec("jobA", lambda: _TagModel("A"), 60),
        ProgramSpec("jobB", lambda: _TagModel("B"), 60),
    ], residency=ResidencyManager(budget_bytes=200))
    out = host.predict([wrap_query("jobA", "x"), wrap_query("jobB", "y"),
                        wrap_query("jobA", "z")])
    assert out == ["A:x", "B:y", "A:z"]
    assert telemetry.get_counter("tenancy.host_queries") == 3


# -- twin: per-tenant model + validation -----------------------------------


def _tenant_capture(tmp_path, per_tenant=30, gap_s=0.02, forward_s=0.010):
    """Synthetic --tenants capture: hop chains + gateway/config for
    calibration, tenant-tagged serving/request rows, tenant/admit
    rows carrying each tenant's tier."""
    overhead = 0.002
    recs = [{"kind": "gateway", "name": "config", "ts": 0.0, "pid": 1,
             "max_inflight": 8, "max_queue": 32,
             "default_deadline_s": 2.0, "min_replies": None,
             "hedge_grace_s": 0.0, "policy": "replicate-all",
             "breaker_failures": 3, "breaker_cooldown_s": 5.0}]
    for i in range(per_tenant * 2):
        tenant = "gold_t" if i % 2 == 0 else "batch_t"
        t0 = 100.0 + i * gap_s
        marks = [["admit", t0, 1], ["queue", t0 + 1e-4, 1],
                 ["enq", t0 + 2e-4, 1], ["deq", t0 + 3e-4, 2],
                 ["fwds", t0 + 4e-4, 2],
                 ["fwd", t0 + 4e-4 + forward_s, 2],
                 ["reply", t0 + 5e-4 + forward_s, 2],
                 ["dec", t0 + 6e-4 + forward_s, 1]]
        recs.append({"kind": "serving", "name": "hops", "ts": t0, "pid": 1,
                     "chains": {"w0": marks}})
        recs.append({"kind": "serving", "name": "request", "ts": t0,
                     "pid": 1, "queries": 1, "ok": True, "hedged": 0,
                     "timeouts": 0, "tenant": tenant,
                     "e2e_s": round(forward_s + overhead, 6)})
        recs.append({"kind": "tenant", "name": "admit", "ts": t0, "pid": 1,
                     "tenant": tenant,
                     "tier": "gold" if tenant == "gold_t" else "batch",
                     "waited_s": 0.0})
    path = tmp_path / "journal-gateway-1.jsonl"
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return tmp_path


def test_tenant_simulation_is_deterministic_and_isolating():
    from rafiki_tpu.obs.twin.calibration import Calibration
    from rafiki_tpu.obs.twin.engine import TwinConfig, simulate

    cal = Calibration.nominal(forward_ms=5.0, workers=2)
    cfg = TwinConfig.from_calibration(
        cal, workers=2, max_inflight=2, max_queue=8,
        tenants={"v": {"weight": 4.0}, "agg": {"weight": 1.0}})
    arrivals = ([(i * 0.02, 1, "v") for i in range(40)]
                + [(0.1 + i * 0.002, 1, "agg") for i in range(200)])
    r1 = simulate(cal, cfg, arrivals, seed=0)
    r2 = simulate(cal, cfg, arrivals, seed=0)
    assert r1["event_log_sha1"] == r2["event_log_sha1"]
    blocks = r1["tenants"]
    # The flooder is the one who sheds; the victim is fully served and
    # its caller-observed p99 is reported alongside post-admission.
    assert blocks["agg"]["shed"] > 0
    assert blocks["agg"]["shed_reasons"].get("tenant_quota", 0) > 0
    assert blocks["v"]["shed"] == 0 and blocks["v"]["ok"] == 40
    assert blocks["v"]["full_p99_ms"] >= blocks["v"]["p99_ms"]


def test_validate_tenants_passes_faithful_fails_doctored(tmp_path):
    from rafiki_tpu.obs.twin import validate as validate_mod

    log_dir = _tenant_capture(tmp_path)
    good = validate_mod.validate_tenants(log_dir, seed=0)
    assert good["ok"] is True and good["gated_tenants"] == 2
    assert set(good["tenants"]) == {"gold_t", "batch_t"}
    assert good["tenants"]["gold_t"]["tier"] == "gold"
    bad = validate_mod.validate_tenants(log_dir, seed=0,
                                        scales={"forward": 0.4})
    assert bad["ok"] is False


# -- arbiter ---------------------------------------------------------------


def _nominal_gate(existing, workers=1, forward_ms=50.0, **kw):
    from rafiki_tpu.obs.twin.calibration import Calibration
    from rafiki_tpu.obs.twin.engine import TwinConfig

    cal = Calibration.nominal(forward_ms=forward_ms, workers=workers)
    cfg = TwinConfig.from_calibration(cal, workers=workers)
    return JobAdmissionGate(cal, cfg, existing=existing, horizon_s=2.0,
                            seed=0, **kw)


def test_gate_rejects_saturating_job_and_journals_verdicts(journaled):
    gate = _nominal_gate({"alice": ("gold", 5.0)})
    ok = gate.admit_job("job-small", "carol", "batch", expected_qps=1.0)
    assert ok["admit"] is True
    assert gate.existing["carol"] == ("batch", 1.0)
    # 25 qps sits in the saturation window: admitted-within-quota load
    # that genuinely overruns capacity (an even bigger flood would be
    # quota-shed back under budget — that's isolation, not admission).
    with pytest.raises(JobRejected) as ei:
        gate.admit_job("job-big", "bob", "std", expected_qps=25.0)
    breaches = ei.value.detail["breaches"]
    assert breaches and breaches[0]["tenant"] == "alice"
    assert breaches[0]["forecast_p99_ms"] > breaches[0]["budget_ms"]
    # A rejected job must NOT join the tracked load.
    assert "bob" not in gate.existing
    journal.close()
    verdicts = [r for r in journal_mod.read_dir(journaled)
                if r.get("kind") == "tenancy" and r.get("name") == "arbiter"]
    assert [v["admit"] for v in verdicts] == [True, False]
    assert telemetry.get_counter("tenancy.jobs_admitted") == 1
    assert telemetry.get_counter("tenancy.jobs_rejected") == 1


def test_gate_from_capture_validates_first(tmp_path):
    log_dir = _tenant_capture(tmp_path)
    gate = JobAdmissionGate.from_capture(log_dir, seed=0)
    assert set(gate.existing) == {"gold_t", "batch_t"}
    assert gate.existing["gold_t"][0] == "gold"
    assert all(qps > 0 for _, qps in gate.existing.values())
    # An absurd tolerance turns the same capture into an unvalidated
    # model — the gate must refuse rather than forecast with it.
    with pytest.raises(ModelUnvalidated):
        JobAdmissionGate.from_capture(log_dir, seed=0, tolerance=1e-6)


def test_tenant_pressure_tracks_worst_component():
    from rafiki_tpu.tenancy.arbiter import tenant_pressure

    p, reason = tenant_pressure({"tenant_burn": 2.0, "queue_frac": 0.1,
                                 "tenant_shed_rate": 0.05})
    assert (p, reason) == (2.0, "tenant_burn")
    p, reason = tenant_pressure({"tenant_burn": 0.1, "queue_frac": 0.2,
                                 "tenant_shed_rate": 0.09})
    assert reason == "tenant_shed" and p == pytest.approx(0.9)
