"""Gateway dynamic microbatching + blackout re-route (docs/serving.md).

The MicroBatcher unit tests pin the flush semantics — size, deadline
(max-wait AND member-deadline triggers), drain, FIFO slicing, error
fan-out — with real (short) waits; the gateway tests then drive the
batched predict path end to end on the in-proc bus, and the blackout
tests pin the bounded re-route that keeps an admitted request alive
when its whole fan-out set dies (the stacked-worker loss case,
chaos scenario ``stacked-worker-loss-fallback``).
"""

import threading
import time

import pytest

from rafiki_tpu import telemetry
from rafiki_tpu.gateway import Gateway, GatewayConfig, MicroBatcher
from rafiki_tpu.predictor import Predictor
from rafiki_tpu.predictor.predictor import GatherReport

from tests.test_gateway import _Serving, _SlowConst, _no_errors


class _Collector:
    """Records every flush the batcher executes and answers members."""

    def __init__(self, fail=False):
        self.flushes = []                 # (n_members, n_queries, reason)
        self.lock = threading.Lock()
        self.fail = fail

    def execute(self, members, reason):
        with self.lock:
            self.flushes.append(
                (len(members), sum(len(m.queries) for m in members), reason))
        if self.fail:
            raise RuntimeError("injected flush failure")
        for m in members:
            m.outputs = [f"out-{q}" for q in m.queries]
            m.flush_reason = reason
            m.done.set()


def _submit(b, queries, deadline_s=5.0):
    return b.submit(queries, time.monotonic() + deadline_s, prefix=[])


def test_max_batch_one_is_invalid():
    # 1 means "batching off" and the gateway never constructs a
    # batcher for it — reaching the class with 1 is a wiring bug.
    with pytest.raises(ValueError):
        MicroBatcher(lambda m, r: None, max_batch=1, max_wait_s=0.01)
    with pytest.raises(ValueError):
        GatewayConfig(max_batch=0)


def test_size_flush_coalesces_to_one_execute():
    col = _Collector()
    b = MicroBatcher(col.execute, max_batch=3, max_wait_s=10.0)
    try:
        members = [_submit(b, [i]) for i in range(3)]
        for m in members:
            assert m.wait(5.0)
        assert col.flushes == [(3, 3, "size")]
        assert [m.outputs for m in members] == [
            ["out-0"], ["out-1"], ["out-2"]]
        assert all(m.flush_reason == "size" for m in members)
    finally:
        b.stop()


def test_deadline_flush_bounds_single_request_latency():
    # The latency floor a lone request pays is max_wait, not "wait for
    # co-batchers forever": it must flush with reason deadline within
    # max_wait plus scheduling slack.
    col = _Collector()
    b = MicroBatcher(col.execute, max_batch=64, max_wait_s=0.05)
    try:
        t0 = time.monotonic()
        m = _submit(b, ["solo"])
        assert m.wait(5.0)
        # lint: disable=RF007 — the delta IS the invariant under test
        elapsed = time.monotonic() - t0
        assert m.flush_reason == "deadline"
        assert elapsed < 0.05 + 0.5, f"flush took {elapsed:.3f}s"
    finally:
        b.stop()


def test_member_deadline_preempts_max_wait():
    # A member whose own deadline (minus reserve) lands before the
    # max-wait expiry pulls the flush forward — waiting must never
    # burn budget the fan-out itself needs.
    col = _Collector()
    b = MicroBatcher(col.execute, max_batch=64, max_wait_s=30.0,
                     reserve_fn=lambda: 0.05)
    try:
        m = _submit(b, ["urgent"], deadline_s=0.2)
        assert m.wait(5.0), "member deadline never triggered a flush"
        assert m.flush_reason == "deadline"
    finally:
        b.stop()


def test_drain_flushes_pending_now():
    col = _Collector()
    b = MicroBatcher(col.execute, max_batch=64, max_wait_s=30.0)
    try:
        m = _submit(b, ["a", "b"])
        assert not m.wait(0.05)  # far from max_wait: still pending
        b.drain()
        assert m.wait(5.0)
        assert m.flush_reason == "drain"
        assert col.flushes == [(1, 2, "drain")]
    finally:
        b.stop()


def test_fifo_take_respects_max_batch_queries():
    # max_batch counts QUERIES, not members; a flush takes whole
    # members FIFO up to the cap, and an oversized member ships alone.
    col = _Collector()
    b = MicroBatcher(col.execute, max_batch=4, max_wait_s=10.0)
    try:
        big = _submit(b, ["q0", "q1", "q2", "q3", "q4"])  # > max_batch
        assert big.wait(5.0)
        assert col.flushes[-1] == (1, 5, "size")
        ms = [_submit(b, ["a", "b"]), _submit(b, ["c", "d"]),
              _submit(b, ["e"])]
        for m in ms[:2]:
            assert m.wait(5.0)
        assert col.flushes[-1] == (2, 4, "size")
        b.drain()
        assert ms[2].wait(5.0)
        assert ms[2].flush_reason == "drain"
    finally:
        b.stop()


def test_execute_exception_fans_to_members():
    col = _Collector(fail=True)
    b = MicroBatcher(col.execute, max_batch=2, max_wait_s=0.01)
    try:
        m = _submit(b, ["x"])
        assert m.wait(5.0)
        assert isinstance(m.error, RuntimeError)
    finally:
        b.stop()


def test_submit_after_stop_raises():
    b = MicroBatcher(_Collector().execute, max_batch=2, max_wait_s=0.01)
    b.stop()
    with pytest.raises(RuntimeError):
        _submit(b, ["late"])


# -- the batched gateway path ------------------------------------------------


def test_gateway_microbatched_end_to_end():
    """Concurrent requests ride ONE shared fan-out: every request gets
    its own correct outputs, the microbatch telemetry populates, and
    the per-request journal semantics (ok, batched) hold."""
    telemetry.reset()
    cluster = _Serving([_SlowConst([0.6, 0.4], 0.005)] * 2)
    try:
        predictor = Predictor(cluster.bus, cluster.job, timeout_s=5.0)
        gw = Gateway(predictor, GatewayConfig(
            min_replies=2, max_batch=4, max_batch_wait_ms=10.0))
        results = {}
        lock = threading.Lock()

        def fire(i):
            out = gw.predict([[float(i)], [float(i) + 0.5]])
            with lock:
                results[i] = out

        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(6)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=15)
        assert len(results) == 6
        for i, out in results.items():
            assert len(out) == 2 and _no_errors(out), (i, out)
            assert out[0] == pytest.approx([0.6, 0.4], abs=1e-6)
        snap = telemetry.snapshot()
        hists = snap.get("histograms", {})
        assert hists["serving.microbatch.size"]["count"] >= 1
        assert hists["serving.microbatch.fill_ratio"]["count"] >= 1
        counters = snap.get("counters", {})
        flushes = sum(counters.get(f"serving.microbatch.flush_{r}", 0)
                      for r in ("size", "deadline", "drain"))
        assert flushes >= 1
        # Coalescing actually happened: fewer flushes than requests.
        assert flushes < 6
        assert gw.stats()["limits"]["max_batch"] == 4
        assert gw.stats()["timeouts"] == 0
    finally:
        cluster.close()


def test_gateway_drain_flushes_microbatch_members():
    telemetry.reset()
    cluster = _Serving([_SlowConst([0.6, 0.4])] * 2)
    try:
        predictor = Predictor(cluster.bus, cluster.job, timeout_s=5.0)
        gw = Gateway(predictor, GatewayConfig(
            min_replies=2, max_batch=64, max_batch_wait_ms=30_000.0))
        out = {}

        def fire():
            out["v"] = gw.predict([[1.0]])

        th = threading.Thread(target=fire)
        th.start()
        deadline = time.monotonic() + 5
        while gw._batcher.pending == 0:
            assert time.monotonic() < deadline, "member never enqueued"
            time.sleep(0.002)
        assert gw.drain(timeout=10.0)
        th.join(timeout=10)
        assert "v" in out and _no_errors(out["v"])
    finally:
        cluster.close()


# -- blackout re-route -------------------------------------------------------


class _ScriptedPredictor:
    """Predictor stand-in whose gathers follow a script: each call pops
    the next reply-count; 0 means a dead fan-out set (zero replies)."""

    job_id = "bljob"
    timeout_s = 5.0

    def __init__(self, script):
        self.script = list(script)
        self.calls = 0

    def live_workers(self):
        return ["w0"]

    def predict_detailed(self, queries, workers=None, timeout_s=None,
                         min_replies=None, hedge_grace_s=None):
        self.calls += 1
        n = self.script.pop(0) if self.script else 1
        if n:
            return GatherReport(outputs=[[0.6, 0.4]] * len(queries),
                                workers=list(workers), quorum=1,
                                replies={w: len(queries) for w in workers},
                                timeouts=0, hedged=0, elapsed_s=0.001)
        return GatherReport(outputs=[{"error": "no predictions"}]
                            * len(queries),
                            workers=list(workers), quorum=1,
                            replies={}, timeouts=len(queries), hedged=0,
                            elapsed_s=timeout_s or 0.0)


def test_blackout_retry_reroutes_dead_fanout():
    telemetry.reset()
    pred = _ScriptedPredictor([0, 1])  # first gather dies, re-route wins
    gw = Gateway(pred, GatewayConfig(min_replies=1, blackout_retries=2))
    gw._latency_ewma_s = 0.01  # latency model exists: probing is armed
    before = telemetry.get_counter("gateway.blackout_retries")
    out = gw.predict([[1.0]], deadline_s=8.0)
    assert _no_errors(out)
    assert pred.calls == 2
    assert telemetry.get_counter("gateway.blackout_retries") == before + 1
    assert gw.stats()["timeouts"] == 0


def test_cold_gateway_does_not_probe():
    # No latency EWMA -> no basis to cut a gather short: the first
    # attempt gets the whole budget and a zero-reply gather surfaces
    # as-is instead of burning the deadline on blind retries.
    telemetry.reset()
    pred = _ScriptedPredictor([0, 1])
    gw = Gateway(pred, GatewayConfig(min_replies=1, blackout_retries=3))
    out = gw.predict([[1.0]], deadline_s=2.0)
    assert pred.calls == 1
    assert isinstance(out[0], dict) and "error" in out[0]


def test_blackout_retries_exhausted_returns_timeouts():
    telemetry.reset()
    pred = _ScriptedPredictor([0, 0, 0])
    gw = Gateway(pred, GatewayConfig(min_replies=1, blackout_retries=2))
    gw._latency_ewma_s = 0.01
    out = gw.predict([[1.0]], deadline_s=3.0)
    assert pred.calls == 3  # 2 probes + 1 final full-budget attempt
    assert isinstance(out[0], dict) and "error" in out[0]
    assert gw.stats()["timeouts"] == 1
