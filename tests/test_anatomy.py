"""Request-anatomy plane (docs/serving_anatomy.md): hop-mark envelope
back-compat, segment math and hop-sum reconciliation, the exemplar
ring's bounds, the serving rollup's determinism, and waterfall
stitching across processes via the real CLI readers."""

import json

import pytest

from rafiki_tpu import telemetry
from rafiki_tpu.bus import InProcBus
from rafiki_tpu.obs import context as trace_context
from rafiki_tpu.obs import journal as journal_mod
from rafiki_tpu.obs.anatomy import hops
from rafiki_tpu.obs.anatomy.exemplars import ExemplarRing
from rafiki_tpu.obs.anatomy.timeseries import ServingRollup
from rafiki_tpu.obs.journal import journal


@pytest.fixture
def journaled(tmp_path):
    journal.configure(tmp_path, role="test")
    try:
        yield tmp_path
    finally:
        journal.close()


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    yield
    telemetry.reset()


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# -- envelope back-compat ----------------------------------------------------


def test_untraced_messages_keep_bare_tuple_shapes():
    bus = InProcBus()
    bus.add_worker("job", "w0")
    bus.add_query("w0", "q1", [1.0])
    (item,) = bus.pop_queries("w0", max_n=4, timeout=0.5)
    assert item == ("q1", [1.0])  # no trace -> no third element
    bus.put_prediction("q1", "w0", [0.5])
    (reply,) = bus.get_predictions("q1", n=1, timeout=0.5)
    assert reply == ("w0", [0.5])


def test_traced_envelope_carries_gateway_prefix_plus_enq_mark():
    bus = InProcBus()
    bus.add_worker("job", "w0")
    hops.begin()
    hops.add("admit")
    hops.add("queue")
    try:
        with trace_context.trace("t-anatomy-1"):
            bus.add_query("w0", "q1", [1.0])
    finally:
        hops.clear()
    (item,) = bus.pop_queries("w0", max_n=4, timeout=0.5)
    assert item[0] == "q1" and len(item) == 3
    marks = item[2]["hops"]
    assert [m[0] for m in marks] == ["admit", "queue", "enq"]
    # [code, monotonic ts, pid]: timestamps ordered, pid stamped
    assert marks[0][1] <= marks[-1][1]
    assert all(isinstance(m[2], int) for m in marks)
    # clear() closed the prefix: the next add is a no-op
    assert hops.add("admit") is None and hops.prefix_marks() == []


def test_explicit_trace_dict_is_not_mutated_by_envelope():
    bus = InProcBus()
    bus.add_worker("job", "w0")
    shared = {"trace_id": "t-shared"}
    bus.add_query("w0", "q1", [1.0], trace=shared)
    assert "hops" not in shared  # caller-owned dict copied, not annotated
    (item,) = bus.pop_queries("w0", max_n=4, timeout=0.5)
    assert item[2]["trace_id"] == "t-shared"
    assert [m[0] for m in item[2]["hops"]] == ["enq"]


def test_reply_hops_ride_as_optional_third_element():
    bus = InProcBus()
    bus.add_worker("job", "w0")
    bus.add_worker("job", "w1")
    chain = [hops.mark("enq"), hops.mark("deq"), hops.mark("reply")]
    bus.put_prediction("q1", "w0", [0.5], hops=chain)
    bus.put_prediction("q1", "w1", [0.4])
    replies = sorted(bus.get_predictions("q1", n=2, timeout=0.5),
                     key=lambda item: item[0])
    # Mixed shapes gather together: consumers index, never destructure.
    assert [len(item) for item in replies] == [3, 2]
    assert replies[0][2] is chain


# -- segment math + reconciliation -------------------------------------------


def _chain(pid, *steps):
    """Build a mark chain from (code, ts) steps with a fixed pid."""
    return [[code, float(ts), pid] for code, ts in steps]


FULL = (("admit", 0.0), ("queue", 0.010), ("enq", 0.012), ("deq", 0.020),
        ("fwds", 0.021), ("fwd", 0.071), ("reply", 0.072), ("dec", 0.080))


def test_segments_name_every_gap_and_sum_to_chain_total():
    marks = _chain(42, *FULL)
    segs = hops.segments(marks)
    assert [s for s, _ in segs] == ["admission_wait", "route", "bus_queue",
                                    "batch_wait", "forward", "reply_publish",
                                    "gather_decide"]
    assert sum(d for _, d in segs) == pytest.approx(
        hops.chain_total_s(marks), abs=1e-9)


def test_unknown_mark_breaks_reconciliation_loudly():
    # A foreign mark advances the clock but names no segment: the
    # hop-sum must fall SHORT of the end-to-end span, never silently
    # absorb the gap into a neighbor.
    marks = _chain(42, ("enq", 0.0), ("mystery", 0.5), ("dec", 0.6))
    segs = hops.segments(marks)
    assert [s for s, _ in segs] == ["gather_decide"]
    assert sum(d for _, d in segs) == pytest.approx(0.1, abs=1e-9)
    assert hops.chain_total_s(marks) == pytest.approx(0.6, abs=1e-9)


def test_absorb_feeds_hop_histograms_and_fanout_cost(journaled):
    fast = _chain(7, *FULL)
    slow = _chain(8, ("enq", 0.012), ("deq", 0.020), ("fwds", 0.021),
                  ("fwdc", 0.171), ("reply", 0.172), ("dec", 0.180))
    total = hops.absorb("q-abs", {"w0": fast, "w1": slow})
    assert total == pytest.approx(0.180 - 0.012)
    hists = telemetry.snapshot()["histograms"]
    assert hists["serving.hop.forward_s"]["count"] == 1
    assert hists["serving.hop.forward_cold_s"]["count"] == 1
    assert hists["serving.hop.bus_queue_s"]["count"] == 2
    # fan-out cost = slowest chain total minus slowest device forward
    fan = hists[hops.FANOUT_METRIC]
    assert fan["count"] == 1
    assert fan["p50"] == pytest.approx((0.180 - 0.012) - 0.150, abs=1e-6)
    recs = [r for r in journal_mod.read_dir(journaled)
            if r["kind"] == "serving" and r["name"] == "hops"]
    assert len(recs) == 1 and recs[0]["query_id"] == "q-abs"
    assert set(recs[0]["chains"]) == {"w0", "w1"}


# -- exemplar ring ------------------------------------------------------------


def test_exemplar_ring_keeps_slowest_n_and_rolls_windows(journaled):
    clock = _Clock()
    ring = ExemplarRing(cap=3, window_s=10.0, clock=clock)
    for i, total in enumerate([0.05, 0.9, 0.1, 0.7, 0.3]):
        ring.offer(total, {"query_id": f"q{i}", "chains": {},
                           "trace_id": f"t{i}"})
    col = ring.collector()
    assert col["retained"] == 3 and col["offered"] == 5
    assert col["slowest_s"] == pytest.approx(0.9)
    # All-numeric leaves: the prom flattener must keep every field.
    assert all(isinstance(v, (int, float)) for v in col.values())

    # Window roll: the NEXT offer past window_s journals the retained
    # slowest-first, with the trace id captured at OFFER time.
    clock.t = 11.0
    ring.offer(0.2, {"query_id": "q5", "chains": {}, "trace_id": "t5"})
    recs = [r for r in journal_mod.read_dir(journaled)
            if r["kind"] == "serving" and r["name"] == "exemplar"]
    assert [r["query_id"] for r in recs] == ["q1", "q3", "q4"]
    assert [r["rank"] for r in recs] == [0, 1, 2]
    assert [r["trace_id"] for r in recs] == ["t1", "t3", "t4"]
    assert ring.collector()["retained"] == 1  # the new window's offer
    assert ring.flush() == 1
    assert ring.collector()["windows_flushed"] == 2


# -- serving rollup -----------------------------------------------------------


def test_rollup_rows_are_deterministic_under_a_fake_clock(journaled):
    clock = _Clock(100.2)
    ctx = {"queue_depth": 3, "inflight": 2}
    rollup = ServingRollup(bucket_s=1.0, clock=clock, context_fn=lambda: ctx)
    for lat in (0.010, 0.020, 0.030, 0.250):
        rollup.observe(latency_s=lat)
    rollup.observe(outcome="shed")
    rollup.observe(outcome="error")
    clock.t = 101.2  # next bucket: first observe there closes the last
    rollup.observe(latency_s=0.005)
    rollup.flush()
    rows = [r for r in journal_mod.read_dir(journaled)
            if r["kind"] == "serving" and r["name"] == "ts"]
    assert len(rows) == 2
    first = rows[0]
    assert (first["bucket"], first["requests"], first["ok"], first["shed"],
            first["errors"]) == (100, 6, 4, 1, 1)
    assert first["qps"] == pytest.approx(6.0)
    # nearest-rank on [10, 20, 30, 250]ms: round(0.5 * 3) = idx 2
    assert first["p50_ms"] == pytest.approx(30.0)
    assert first["p99_ms"] == pytest.approx(250.0)
    assert first["shed_rate"] == pytest.approx(1 / 6, abs=1e-4)
    assert first["queue_depth"] == 3 and first["inflight"] == 2
    gauges = telemetry.snapshot()["gauges"]
    assert gauges["serving.qps"] == pytest.approx(1.0)  # the flushed bucket
    col = rollup.collector()
    assert col["buckets_flushed"] == 2
    assert col["last"]["requests"] == 1


def test_rollup_empty_bucket_journals_nothing(journaled):
    rollup = ServingRollup(bucket_s=1.0, clock=_Clock())
    assert rollup.flush() is None
    assert [r for r in journal_mod.read_dir(journaled)
            if r["kind"] == "serving"] == []


# -- waterfall stitching across processes (the CLI readers) -------------------


def _write_journal(tmp_path, name, records):
    with open(tmp_path / name, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def test_waterfall_stitches_three_pids_and_reconciles(tmp_path, capsys):
    from rafiki_tpu.obs import cli

    # Hand-written journals from three processes: the gateway journaled
    # the hops record (absorb runs in the gateway/predictor process)
    # with chains whose marks were stamped by gateway pid 100 and the
    # two worker pids 101/102.
    chain_a = (_chain(100, ("admit", 0.0), ("queue", 0.010), ("enq", 0.012))
               + _chain(101, ("deq", 0.020), ("fwds", 0.021), ("fwd", 0.071),
                        ("reply", 0.072))
               + _chain(100, ("dec", 0.080)))
    chain_b = (_chain(100, ("admit", 0.0), ("queue", 0.010), ("enq", 0.012))
               + _chain(102, ("deq", 0.025), ("fwds", 0.026), ("fwd", 0.076),
                        ("reply", 0.077))
               + _chain(100, ("dec", 0.080)))
    _write_journal(tmp_path, "journal-gateway-100.jsonl", [
        {"ts": 1.0, "pid": 100, "kind": "serving", "name": "hops",
         "trace_id": "feedface01", "query_id": "q-wf",
         "chains": {"w0": chain_a, "w1": chain_b}, "total_s": 0.08},
        {"ts": 1.1, "pid": 100, "kind": "serving", "name": "request",
         "trace_id": "feedface01", "queries": 1, "e2e_s": 0.081, "ok": True},
    ])
    _write_journal(tmp_path, "journal-infer-101.jsonl", [
        {"ts": 0.9, "pid": 101, "kind": "bus", "name": "pop_query",
         "trace_id": "feedface01", "query_id": "q-wf"},
    ])

    assert cli.cmd_waterfall(str(tmp_path), "feedface", as_json=True) == 0
    doc = json.loads(capsys.readouterr().out)
    (q,) = doc["queries"]
    assert q["n_hops"] == 8
    assert q["pids"] == [100, 101, 102]
    assert q["max_reconcile_err"] <= 1e-9
    assert doc["e2e_s"] == pytest.approx(0.081)

    # Tail attribution over the same records reconciles fleet-wide.
    assert cli.cmd_tails(str(tmp_path), as_json=True, check=True,
                         tolerance=0.10) == 0
    tails = json.loads(capsys.readouterr().out)
    assert tails["reconcile"]["ok"] is True
    assert {s["segment"] for s in tails["segments"]} >= {"forward",
                                                         "bus_queue"}


def test_waterfall_unknown_trace_exits_nonzero(tmp_path, capsys):
    from rafiki_tpu.obs import cli

    assert cli.cmd_waterfall(str(tmp_path), "nope", as_json=True) == 1
    assert "no serving hop records" in capsys.readouterr().err


# -- prom exposition ----------------------------------------------------------


def test_hop_histograms_flatten_into_prom_exposition(journaled):
    from rafiki_tpu.obs import prom

    hops.absorb("q-prom", {"w0": _chain(7, *FULL)})
    text = prom.to_prometheus(telemetry.snapshot())
    assert 'rafiki_serving_hop_forward_s{quantile="0.99"}' in text
    assert "rafiki_serving_hop_forward_s_count 1" in text
    assert "rafiki_serving_hop_admission_wait_s_count 1" in text
