"""Admin business logic: full in-process AutoML lifecycle (no HTTP)."""

import pytest

from rafiki_tpu.admin import Admin
from rafiki_tpu.utils.auth import AuthError

FF_SOURCE = b"""
from rafiki_tpu.model.base import JaxModel
from rafiki_tpu.model.knobs import CategoricalKnob, FixedKnob, FloatKnob

class TinyFF(JaxModel):
    @staticmethod
    def get_knob_config():
        return {
            "hidden_units": CategoricalKnob([16, 32], affects_shape=True),
            "learning_rate": FloatKnob(1e-3, 3e-2, is_exp=True),
            "batch_size": FixedKnob(32),
            "epochs": FixedKnob(1),
        }

    def build_module(self, num_classes, input_shape):
        from rafiki_tpu.models.ff import _Mlp
        return _Mlp(hidden_layers=1, hidden_units=int(self.knobs["hidden_units"]),
                    num_classes=num_classes)
"""

TRAIN = "synthetic://images?classes=5&n=256&w=8&h=8&seed=0"
VAL = "synthetic://images?classes=5&n=128&w=8&h=8&seed=1"


@pytest.fixture()
def admin(tmp_config):
    a = Admin(config=tmp_config)
    yield a
    a.stop()


def test_superadmin_seeded_and_login(admin, tmp_config):
    out = admin.authenticate_user(tmp_config.superadmin_email,
                                  tmp_config.superadmin_password)
    assert out["user_type"] == "SUPERADMIN"
    assert out["token"]
    with pytest.raises(AuthError):
        admin.authenticate_user(tmp_config.superadmin_email, "wrong")


def test_user_lifecycle(admin):
    u = admin.create_user("dev@x", "pw", "MODEL_DEVELOPER")
    assert u["user_type"] == "MODEL_DEVELOPER"
    with pytest.raises(ValueError):
        admin.create_user("dev@x", "pw", "MODEL_DEVELOPER")  # duplicate
    with pytest.raises(ValueError):
        admin.create_user("z@x", "pw", "WIZARD")  # bad role
    admin.ban_user("dev@x")
    with pytest.raises(AuthError, match="banned"):
        admin.authenticate_user("dev@x", "pw")


def test_model_upload_validation(admin):
    with pytest.raises(ValueError, match="Invalid model template"):
        admin.create_model(None, "bad", "IMAGE_CLASSIFICATION",
                           b"this is not python ][", "Nope")
    m = admin.create_model(None, "tinyff", "IMAGE_CLASSIFICATION",
                           FF_SOURCE, "TinyFF")
    assert m["name"] == "tinyff"
    assert admin.get_model("tinyff")["model_class"] == "TinyFF"
    assert admin.get_model_file("tinyff") == FF_SOURCE


def test_train_job_budget_validation(admin):
    admin.create_model(None, "tinyff", "IMAGE_CLASSIFICATION", FF_SOURCE, "TinyFF")
    with pytest.raises(ValueError, match="[Bb]udget"):
        admin.create_train_job(None, "app", "IMAGE_CLASSIFICATION", TRAIN, VAL, {},
                               start=False)
    with pytest.raises(ValueError, match="Unknown budget keys"):
        admin.create_train_job(None, "app", "IMAGE_CLASSIFICATION", TRAIN, VAL,
                               {"COFFEE_COUNT": 3}, start=False)
    with pytest.raises(ValueError, match="No models"):
        admin.create_train_job(None, "app", "POS_TAGGING", TRAIN, VAL,
                               {"MODEL_TRIAL_COUNT": 1}, start=False)


def test_full_automl_lifecycle(admin):
    """Train → best trials → inference job → predict → stop. The whole
    reference user journey (SURVEY.md §3.1–3.2) in one process."""
    admin.create_model(None, "tinyff", "IMAGE_CLASSIFICATION", FF_SOURCE, "TinyFF")
    job = admin.create_train_job(None, "myapp", "IMAGE_CLASSIFICATION",
                                 TRAIN, VAL, {"MODEL_TRIAL_COUNT": 3},
                                 advisor_kind="random")
    assert job["app_version"] == 1
    done = admin.wait_train_job("myapp", timeout=300)
    assert done["status"] == "COMPLETED"

    trials = admin.get_trials_of_train_job("myapp")
    assert len(trials) == 3
    best = admin.get_best_trials_of_train_job("myapp", max_count=2)
    assert len(best) == 2
    assert best[0]["score"] >= best[1]["score"]
    assert admin.get_trial(best[0]["id"])["status"] == "COMPLETED"
    assert len(admin.get_trial_parameters(best[0]["id"])) > 100
    logs = admin.get_trial_logs(best[0]["id"])
    assert any("loss" in str(e) or "epoch" in str(e) for e in logs)

    # premature inference job on a second app fails cleanly
    with pytest.raises(KeyError):
        admin.create_inference_job(None, "nosuchapp")

    inf = admin.create_inference_job(None, "myapp")
    assert inf["status"] == "RUNNING"
    import numpy as np
    queries = np.random.default_rng(0).uniform(0, 1, size=(4, 8, 8, 1)).tolist()
    preds = admin.predict("myapp", queries)
    assert len(preds) == 4
    assert all(len(p) == 5 for p in preds)          # 5-class prob vectors
    assert abs(sum(preds[0]) - 1.0) < 1e-3

    with pytest.raises(ValueError, match="already has a running inference job"):
        admin.create_inference_job(None, "myapp")

    admin.stop_inference_job("myapp")
    with pytest.raises(KeyError):
        admin.get_inference_job("myapp")


def test_stop_train_job(admin):
    admin.create_model(None, "tinyff", "IMAGE_CLASSIFICATION", FF_SOURCE, "TinyFF")
    admin.create_train_job(None, "stopapp", "IMAGE_CLASSIFICATION", TRAIN, VAL,
                           {"MODEL_TRIAL_COUNT": 50}, advisor_kind="random")
    out = admin.stop_train_job("stopapp")
    assert out["status"] in ("STOPPED", "COMPLETED")
    job = admin.wait_train_job("stopapp", timeout=60)
    assert job["status"] in ("STOPPED", "COMPLETED")
    # far fewer than 50 trials actually ran
    assert len(admin.get_trials_of_train_job("stopapp")) < 50
