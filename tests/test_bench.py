"""bench.py contract: ONE parseable JSON line on stdout, always.

The driver parses bench.py's stdout; BENCH_r01 failed with
`parsed: null` when the TPU tunnel hung the backend init. These tests
pin the hardened contract: success, forced failure, and watchdog
deadline all still emit the JSON line (with an "error" field and
partial detail on the failure paths).
"""

import json
import os
import subprocess
import sys
from pathlib import Path

BENCH = str(Path(__file__).resolve().parent.parent / "bench.py")


def _run(env_extra: dict, timeout: int = 600):
    env = dict(os.environ)
    env.update(env_extra)
    r = subprocess.run([sys.executable, BENCH], capture_output=True,
                       text=True, timeout=timeout, env=env)
    lines = [l for l in r.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, f"expected exactly one JSON line, got: {r.stdout!r}"
    return r.returncode, json.loads(lines[0])


def test_bench_smoke_cpu():
    rc, out = _run({"RAFIKI_BENCH_PLATFORM": "cpu", "RAFIKI_BENCH_TRIALS": "3"})
    assert rc == 0
    assert out["metric"] == "cifar10_automl_trials_per_hour"
    assert out["value"] > 0
    assert out["vs_baseline"] > 0
    assert "error" not in out
    d = out["detail"]
    # the headline is the measured real-loop number, compile-inclusive
    assert d["measured_trials"] == 3
    assert d["measured_trials_per_hour"] == out["value"]
    assert d["job_status"] == "COMPLETED"
    assert d["programs_compiled"] >= 1
    # trials beyond the shape buckets must hit the program cache
    assert d["program_cache_hits"] >= 1
    assert d["advisor_s_per_trial_at_30obs"] >= 0
    assert "estimate" in d["baseline_basis"].lower()
    # the accuracy clause is calibrated + gated on TPU; on a plain CPU
    # smoke run a 3-trial sweep misses the target by seed noise, so a
    # miss stays ADVISORY (top1_note, rc 0) — BENCH_r03–r05 turned rc=1
    # on exactly this, zeroing the perf trajectory
    assert d["best_top1"] is not None
    if d["top1_miss"]:
        assert "below smoke target" in d["top1_note"]
    else:
        assert d["best_top1"] >= d["top1_target"]
    assert d["top1_ceiling"] < 0.9  # flip-noise ceiling, not a saturating task
    # goodput ledger present, wall decomposed per trial (docs/observability.md)
    g = d["goodput"]
    assert g["total"]["step_s"] > 0
    assert g["goodput"] >= 0.0
    assert any(e.startswith("trial:") for e in g["entities"])
    # acceptance config 5 is an actual k>=2 ensemble, stacked path engaged
    assert d["serving_k"] == 2
    assert d["serving_path"] == "stacked"
    assert d["serving_qps_stacked"] > 0
    assert d["serving_qps_per_worker"] > 0
    # GP-vs-random lift from real tiny trials, >=3 seeds + dispersion
    assert "advisor_lift" in d
    assert len(d["advisor_lift_per_seed"]) >= 3
    assert d["advisor_lift_spread"] >= 0
    assert isinstance(d["advisor_lift_significant"], bool)
    # honesty details
    assert d["n_workers"] == 1
    # steady = trials started after the last cold compile; may be null
    # on a short smoke run where every trial overlapped a compile
    if d["steady_trial_s"] is not None:
        assert 0 < d["steady_trial_s"] <= d["slowest_trial_s"]
        assert d["steady_trials_n"] >= 1
    assert "whole-program" in d["mfu_basis"]
    # MFU vs a TPU peak is meaningless off-TPU: must be null, not 0.0
    assert d["mfu_vs_v5e_bf16_peak"] is None
    assert d["mfu_model_flops"] is None
    # time-to-target: positive wall-clock when some trial crossed the
    # target, null (never a zero) on an advisory miss
    if not d["top1_miss"]:
        assert d["wall_s_to_top1_target"] > 0
    else:
        assert d["wall_s_to_top1_target"] is None


def test_bench_top1_gate_turns_red():
    """An unreachable target must flip the bench to an error exit: the
    accuracy clause is falsifiable, not decorative."""
    rc, out = _run({"RAFIKI_BENCH_PLATFORM": "cpu", "RAFIKI_BENCH_TRIALS": "3",
                    "RAFIKI_BENCH_TOP1_TARGET": "0.99"})
    assert rc == 1
    assert "below target" in out["error"]
    assert out["detail"]["top1_miss"] is True
    assert out["value"] > 0  # the measured headline still reported


def test_bench_degraded_fallback_exits_green():
    """TPU tunnel down → CPU fallback: the artifact must be an HONEST
    reduced data point (degraded marker, null headline, microbench +
    goodput ledger), not an rc=1 zero (BENCH_r03–r05)."""
    rc, out = _run({"RAFIKI_BENCH_SELFTEST_DEGRADED": "1"}, timeout=300)
    assert rc == 0
    assert "error" not in out
    assert out["value"] is None
    assert out["vs_baseline"] is None
    d = out["detail"]
    assert "degraded" in d
    assert "degraded_micro_error" not in d
    # the microbench still measured something real
    assert d["trial_pack"]["packed_s_per_trial"] > 0
    # goodput ledger present on the degraded artifact too
    g = d["goodput"]
    assert g["entities"]["bench:micro"]["step_s"] > 0
    assert g["goodput"] >= 0.0


def test_bench_forced_failure_still_emits_json():
    rc, out = _run({"RAFIKI_BENCH_SELFTEST_FAIL": "1"}, timeout=120)
    assert rc == 1
    assert "error" in out and "forced backend failure" in out["error"]
    assert out["metric"] == "cifar10_automl_trials_per_hour"
    assert out["value"] == 0.0


def test_bench_deadline_watchdog_emits_json():
    # The selftest stall (after backend init) guarantees the 10s
    # watchdog fires mid-run regardless of cache warmth.
    rc, out = _run({"RAFIKI_BENCH_PLATFORM": "cpu",
                    "RAFIKI_BENCH_DEADLINE_S": "10",
                    "RAFIKI_BENCH_SELFTEST_SLEEP_S": "60"}, timeout=180)
    assert rc == 3
    assert "deadline exceeded" in out["error"]
    # partial detail survived
    assert "device" in out["detail"]
