"""Unified telemetry layer: registry thread-safety, span nesting,
snapshot/dump_jsonl, the /metrics endpoints, and program-cache
re-export through the registry."""

import json
import threading

import pytest

from rafiki_tpu import telemetry


@pytest.fixture(autouse=True)
def fresh_telemetry():
    """Each test starts from zeroed metrics (collectors stay: they
    register once at module import)."""
    telemetry.reset()
    yield
    telemetry.reset()


# -- registry ----------------------------------------------------------------


def test_counter_gauge_basics():
    telemetry.inc("a")
    telemetry.inc("a", 2.5)
    assert telemetry.get_counter("a") == 3.5
    assert telemetry.get_counter("missing") == 0.0
    telemetry.set_gauge("g", 7)
    telemetry.add_gauge("g", -2)
    assert telemetry.get_gauge("g") == 5.0


def test_registry_thread_safety():
    n_threads, n_incs = 8, 5000

    def work():
        for _ in range(n_incs):
            telemetry.inc("hammer")
            telemetry.observe("hist", 1.0)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert telemetry.get_counter("hammer") == n_threads * n_incs
    snap = telemetry.snapshot()
    assert snap["histograms"]["hist"]["count"] == n_threads * n_incs


def test_histogram_summary_and_reservoir_bound():
    for v in range(1, 2001):  # > reservoir cap: must stay bounded
        telemetry.observe("h", float(v))
    h = telemetry.snapshot()["histograms"]["h"]
    assert h["count"] == 2000
    assert h["min"] == 1.0 and h["max"] == 2000.0
    assert h["sum"] == pytest.approx(2001000.0)
    # Percentiles come from a uniform reservoir sample: loose sanity.
    assert 0 < h["p50"] <= 2000
    assert h["p50"] <= h["p90"] <= h["p99"]


def test_collector_appears_in_snapshot_and_survives_errors():
    # clear_collectors wipes import-time registrations too (e.g. the
    # ops.train program_cache collector, which only re-registers on a
    # fresh import) — save and restore them around the wipe.
    saved = dict(telemetry.get_registry()._collectors)
    try:
        telemetry.register_collector("mystats", lambda: {"x": 1})
        telemetry.register_collector("broken", lambda: 1 / 0)
        snap = telemetry.snapshot()
        assert snap["mystats"] == {"x": 1}
        assert "error" in snap["broken"]
        telemetry.get_registry().register_collector("mystats", lambda: {"x": 2})
        assert telemetry.snapshot()["mystats"] == {"x": 2}  # re-register replaces
        telemetry.reset(clear_collectors=True)
        assert "mystats" not in telemetry.snapshot()
    finally:
        for name, fn in saved.items():
            telemetry.register_collector(name, fn)


# -- spans -------------------------------------------------------------------


def test_span_nesting_records_parent():
    with telemetry.span("outer", job="j1"):
        with telemetry.span("inner"):
            pass
    recs = {r["name"]: r for r in telemetry.span_records()}
    assert recs["inner"]["parent"] == "outer"
    assert recs["outer"]["parent"] is None
    assert recs["outer"]["tags"] == {"job": "j1"}
    summary = telemetry.snapshot()["spans"]
    assert summary["outer"]["count"] == 1
    assert summary["outer"]["total_s"] >= summary["inner"]["total_s"] >= 0


def test_span_stack_is_per_thread():
    seen = {}

    def work(name):
        with telemetry.span(name):
            seen[name] = True

    threads = [threading.Thread(target=work, args=(f"t{i}",)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # No cross-thread parenting: every thread's span is a root span.
    assert all(r["parent"] is None for r in telemetry.span_records())


def test_span_records_exception_and_reraises():
    with pytest.raises(ValueError):
        with telemetry.span("boom"):
            raise ValueError("x")
    (rec,) = telemetry.span_records()
    assert rec["name"] == "boom" and rec["error"] is True
    # The stack unwound: the next span is a root, not a child of boom.
    with telemetry.span("after"):
        pass
    assert telemetry.span_records()[-1]["parent"] is None


def test_dump_jsonl_and_snapshot_roundtrip(tmp_path):
    telemetry.inc("c", 2)
    with telemetry.span("phase"):
        pass
    path = tmp_path / "telemetry.jsonl"
    n = telemetry.dump_jsonl(path)
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert len(lines) == n == 2
    assert lines[0]["type"] == "span" and lines[0]["name"] == "phase"
    snap = lines[-1]
    assert snap["type"] == "snapshot"
    assert snap["counters"]["c"] == 2
    assert snap["spans"]["phase"]["count"] == 1
    json.dumps(telemetry.snapshot())  # always JSON-able


# -- program cache re-export -------------------------------------------------


def test_program_cache_stats_visible_through_registry():
    from rafiki_tpu.ops import train as ops_train

    ops_train.clear_program_cache()
    sentinel = object()
    key = ("telemetry-test", None, True)
    assert ops_train.get_program(key, lambda: sentinel) is sentinel  # miss
    assert ops_train.get_program(key, lambda: None) is sentinel      # hit
    snap = telemetry.snapshot()
    assert snap["program_cache"]["misses"] >= 1
    assert snap["program_cache"]["hits"] >= 1
    assert snap["counters"]["program_cache.misses"] >= 1
    assert snap["counters"]["program_cache.hits"] >= 1
    assert snap["spans"]["program.build"]["count"] >= 1
    ops_train.clear_program_cache()


# -- /metrics endpoints ------------------------------------------------------


def test_admin_metrics_endpoint(tmp_config):
    from werkzeug.test import Client

    from rafiki_tpu.admin import Admin
    from rafiki_tpu.admin.app import AdminApp

    admin = Admin(config=tmp_config)
    try:
        telemetry.inc("test.admin_metric", 3)
        client = Client(AdminApp(admin))
        resp = client.get("/metrics")  # no auth required, like /healthz
        assert resp.status_code == 200
        body = json.loads(resp.get_data(as_text=True))
        assert body["counters"]["test.admin_metric"] == 3
        # Same registry state as the in-process API, not a copy.
        assert body["counters"] == telemetry.snapshot()["counters"]
    finally:
        admin.stop()


def test_predictor_metrics_endpoint():
    from werkzeug.test import Client

    from rafiki_tpu.bus import InProcBus
    from rafiki_tpu.predictor.app import PredictorApp
    from rafiki_tpu.predictor.predictor import Predictor

    telemetry.inc("test.pred_metric")
    with telemetry.span("test.pred_span"):
        pass
    app = PredictorApp(Predictor(InProcBus(), "nojob"))
    resp = Client(app).get("/metrics")
    assert resp.status_code == 200
    body = json.loads(resp.get_data(as_text=True))
    assert body["counters"]["test.pred_metric"] == 1
    assert body["spans"]["test.pred_span"]["count"] == 1


# -- serving-path introspection ----------------------------------------------


def test_predictor_no_live_workers_is_counted_and_raised():
    from rafiki_tpu.bus import InProcBus
    from rafiki_tpu.predictor.predictor import Predictor

    import time as _time

    bus = InProcBus()
    bus.add_worker("j", "w-dead")
    _time.sleep(0.01)
    # Stale lease (no heartbeat): the predictor must fail fast, not fan
    # out to the corpse and report per-query timeouts.
    pred = Predictor(bus, "j", timeout_s=0.5, worker_ttl_s=0.0)
    with pytest.raises(RuntimeError, match="no live inference workers"):
        pred.predict([[1.0]])
    assert telemetry.get_counter("predictor.no_live_workers") == 1


def test_bus_reap_stale_removes_corpse_and_counts():
    import time as _time

    from rafiki_tpu.bus import InProcBus

    bus = InProcBus()
    bus.add_worker("j", "w1")
    bus.add_worker("j", "w2")
    bus.add_query("w1", "q1", [1.0])
    _time.sleep(0.05)
    bus.heartbeat("j", "w2")  # w2 stays fresh, w1 goes stale
    reaped = bus.reap_stale(max_age_s=0.04, job_id="j")
    assert reaped == [("j", "w1")]
    assert bus.get_workers("j") == ["w2"]
    assert bus.pop_queries("w1", timeout=0.01) == []  # queue deleted too
    assert telemetry.get_counter("bus.reaped_workers") == 1
    # Reaping never touches fresh leases.
    assert bus.reap_stale(max_age_s=60.0) == []


def test_mp_bus_reap_stale_same_contract():
    from rafiki_tpu.bus import make_mp_bus

    bus = make_mp_bus()
    bus.add_worker("j", "w1")
    bus.add_query("w1", "q1", [1.0])
    assert bus.reap_stale(max_age_s=60.0) == []       # fresh: kept
    reaped = bus.reap_stale(max_age_s=-1.0)           # force-stale: reaped
    assert reaped == [("j", "w1")]
    assert bus.get_workers("j") == []
    assert bus.pop_queries("w1", timeout=0.01) == []


def test_bus_heartbeat_of_unknown_job_does_not_leak():
    from rafiki_tpu.bus import InProcBus

    bus = InProcBus()
    for i in range(50):  # defaultdict used to materialize one set per probe
        bus.heartbeat(f"ghost-{i}", "w")
        bus.get_workers(f"ghost2-{i}")
    assert bus._workers == {}
