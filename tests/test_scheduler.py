"""End-to-end train-job scheduling on the fake 8-chip CPU pod."""

import threading

import pytest

from rafiki_tpu.scheduler import LocalScheduler
from rafiki_tpu.store import MetaStore, ParamsStore

FF_SOURCE = b"""
from rafiki_tpu.model.base import JaxModel
from rafiki_tpu.model.knobs import CategoricalKnob, FixedKnob, FloatKnob, IntegerKnob
from rafiki_tpu.models.ff import _Mlp

class TinyFF(JaxModel):
    @staticmethod
    def get_knob_config():
        return {
            "hidden_units": CategoricalKnob([16, 32], affects_shape=True),
            "learning_rate": FloatKnob(1e-3, 3e-2, is_exp=True),
            "batch_size": FixedKnob(32),
            "epochs": FixedKnob(1),
        }

    def build_module(self, num_classes, input_shape):
        return _Mlp(hidden_layers=1, hidden_units=int(self.knobs["hidden_units"]),
                    num_classes=num_classes)
"""

TRAIN = "synthetic://images?classes=5&n=256&w=8&h=8&seed=0"
VAL = "synthetic://images?classes=5&n=128&w=8&h=8&seed=1"


@pytest.fixture()
def env(tmp_path):
    store = MetaStore(tmp_path / "meta.sqlite3")
    params = ParamsStore(tmp_path / "params")
    model = store.create_model("tinyff", "IMAGE_CLASSIFICATION", None, FF_SOURCE, "TinyFF")
    return store, params, model


def _make_job(store, model, budget):
    job = store.create_train_job("myapp", "IMAGE_CLASSIFICATION", None, TRAIN, VAL, budget)
    store.create_sub_train_job(job["id"], model["id"])
    return job


def test_train_job_trial_count_budget(env):
    store, params, model = env
    job = _make_job(store, model, {"MODEL_TRIAL_COUNT": 4})
    sched = LocalScheduler(store, params)
    result = sched.run_train_job(job["id"], n_workers=2, advisor_kind="random")
    assert result.status == "COMPLETED"
    assert len(result.trials) == 4  # atomic claim: never overshoots
    completed = [t for t in result.trials if t["status"] == "COMPLETED"]
    assert len(completed) == 4
    assert all(t["params_id"] for t in completed)
    assert result.best_trials[0]["score"] >= max(t["score"] for t in completed) - 1e-9
    # params are loadable
    blob = params.load(result.best_trials[0]["params_id"])
    assert len(blob) > 100


def test_parallel_workers_share_budget(env):
    store, params, model = env
    job = _make_job(store, model, {"MODEL_TRIAL_COUNT": 6})
    sched = LocalScheduler(store, params)
    result = sched.run_train_job(job["id"], n_workers=4, advisor_kind="random")
    assert len(result.trials) == 6
    workers = {t["worker_id"] for t in result.trials}
    assert len(workers) >= 2  # work actually spread across workers


def test_erroring_model_contained(env):
    store, params, model = env
    bad_src = b"""
from rafiki_tpu.model.base import BaseModel
from rafiki_tpu.model.knobs import FloatKnob

class Bad(BaseModel):
    @staticmethod
    def get_knob_config():
        return {"lr": FloatKnob(0.0, 1.0)}
    def train(self, uri):
        raise RuntimeError("bad knob region" if self.knobs["lr"] > 0.5 else "always bad")
    def evaluate(self, uri):
        return 0.0
    def predict(self, queries):
        return []
"""
    bad = store.create_model("bad", "IMAGE_CLASSIFICATION", None, bad_src, "Bad")
    job = store.create_train_job("badapp", "IMAGE_CLASSIFICATION", None, TRAIN, VAL,
                                 {"MODEL_TRIAL_COUNT": 3})
    store.create_sub_train_job(job["id"], bad["id"])
    sched = LocalScheduler(store, params)
    result = sched.run_train_job(job["id"], n_workers=2, advisor_kind="random")
    # The loop survives (containment) but the job is honestly ERRORED:
    # every trial of its only model failed.
    assert result.status == "ERRORED"
    assert len(result.trials) == 3
    assert all(t["status"] == "ERRORED" for t in result.trials)
    assert "bad" in (result.trials[0]["error"] or "")


def test_stop_event_halts_job(env):
    store, params, model = env
    # Budget far beyond what fits in the timer window: with the
    # program cache, warm trials run in tens of milliseconds, so a
    # small budget would complete before the stop fires.
    job = _make_job(store, model, {"MODEL_TRIAL_COUNT": 100_000})
    sched = LocalScheduler(store, params)
    stop = threading.Event()

    timer = threading.Timer(4.0, stop.set)
    timer.start()
    result = sched.run_train_job(job["id"], n_workers=2, advisor_kind="random",
                                 stop_event=stop)
    timer.cancel()
    assert result.status == "STOPPED"
    assert len(result.trials) < 100_000


def test_trial_logs_captured(env):
    store, params, model = env
    job = _make_job(store, model, {"MODEL_TRIAL_COUNT": 1})
    sched = LocalScheduler(store, params)
    result = sched.run_train_job(job["id"], n_workers=1, advisor_kind="random")
    logs = store.get_trial_logs(result.trials[0]["id"])
    assert any(e["type"] == "plot" for e in logs)
    assert any(e["type"] == "values" and "loss" in e.get("values", {}) for e in logs)
