"""Async parameter persistence: durability, ordering, failure containment."""

import time

import pytest

from rafiki_tpu.advisor import AdvisorService
from rafiki_tpu.model.base import load_model_class
from rafiki_tpu.store import MetaStore, ParamsStore
from rafiki_tpu.worker.train import InProcAdvisorHandle, TrainWorker

from tests.test_scheduler import FF_SOURCE, TRAIN, VAL


@pytest.fixture()
def env(tmp_path):
    store = MetaStore(tmp_path / "meta.sqlite3")
    params = ParamsStore(tmp_path / "params")
    model_row = store.create_model("tinyff", "IMAGE_CLASSIFICATION", None,
                                   FF_SOURCE, "TinyFF")
    job = store.create_train_job("aspp", "IMAGE_CLASSIFICATION", None,
                                 TRAIN, VAL, {"MODEL_TRIAL_COUNT": 3})
    sub = store.create_sub_train_job(job["id"], model_row["id"])
    cls = load_model_class(model_row["model_file"], "TinyFF")
    advisors = AdvisorService()
    aid = advisors.create_advisor(cls.get_knob_config(), kind="random")
    return store, params, job, sub, cls, InProcAdvisorHandle(advisors, aid)


def test_async_persist_all_durable_after_run(env):
    store, params, job, sub, cls, advisor = env
    worker = TrainWorker(store, params, sub["id"], cls, advisor,
                         TRAIN, VAL, job["budget"], async_persist=True)
    n = worker.run()
    assert n == 3
    trials = store.get_trials_of_sub_train_job(sub["id"])
    assert len(trials) == 3
    # flush() in run() guarantees every trial is terminal + durable
    assert all(t["status"] == "COMPLETED" for t in trials)
    for t in trials:
        assert t["params_id"] and len(params.load(t["params_id"])) > 100


def test_sync_and_async_agree(env):
    store, params, job, sub, cls, advisor = env
    w = TrainWorker(store, params, sub["id"], cls, advisor, TRAIN, VAL,
                    {"MODEL_TRIAL_COUNT": 1}, async_persist=False)
    assert w.run() == 1
    t = store.get_trials_of_sub_train_job(sub["id"])[0]
    assert t["status"] == "COMPLETED" and t["params_id"]


def test_persist_failure_marks_trial_errored(env, monkeypatch):
    store, params, job, sub, cls, advisor = env

    def boom(blob, params_id=None):
        raise OSError("disk full")

    monkeypatch.setattr(params, "save", boom)
    worker = TrainWorker(store, params, sub["id"], cls, advisor,
                         TRAIN, VAL, {"MODEL_TRIAL_COUNT": 1},
                         async_persist=True)
    worker.run()
    t = store.get_trials_of_sub_train_job(sub["id"])[0]
    assert t["status"] == "ERRORED"
    assert "params persist failed" in t["error"]
