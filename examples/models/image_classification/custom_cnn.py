"""Example model template: a small CNN a model developer would upload.

Reference parity: examples/models/image_classification/*.py
(unverified — SURVEY.md §2 "Example model zoo"): a standalone .py
implementing the model contract, with an ``if __name__ == "__main__"``
block running the developer harness — the reference's de-facto unit
test (SURVEY.md §4).

Upload with:
    client.create_model("custom_cnn", "IMAGE_CLASSIFICATION",
                        "examples/models/image_classification/custom_cnn.py",
                        "CustomCnn")
"""

try:
    import rafiki_tpu  # noqa: F401 — already importable when uploaded
except ModuleNotFoundError:  # run as a script from a checkout
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[3]))

from flax import linen as nn

from rafiki_tpu.model.base import JaxModel
from rafiki_tpu.model.knobs import (
    CategoricalKnob,
    FixedKnob,
    FloatKnob,
    IntegerKnob,
)


class _Cnn(nn.Module):
    """Conv stack sized by knobs; NHWC, bf16-friendly."""

    base_filters: int
    conv_blocks: int
    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        for i in range(self.conv_blocks):
            x = nn.Conv(self.base_filters * (2 ** i), (3, 3), padding="SAME")(x)
            x = nn.relu(x)
            x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes)(x)


class CustomCnn(JaxModel):
    @staticmethod
    def get_knob_config():
        return {
            "base_filters": CategoricalKnob([16, 32], affects_shape=True),
            "conv_blocks": IntegerKnob(1, 3, affects_shape=True),
            "learning_rate": FloatKnob(1e-4, 1e-1, is_exp=True),
            "batch_size": CategoricalKnob([64, 128]),
            "epochs": FixedKnob(2),
        }

    def build_module(self, num_classes, input_shape):
        return _Cnn(base_filters=int(self.knobs["base_filters"]),
                    conv_blocks=int(self.knobs["conv_blocks"]),
                    num_classes=num_classes)


if __name__ == "__main__":
    import numpy as np

    from rafiki_tpu.model.dev import test_model_class

    rng = np.random.default_rng(0)
    score, preds = test_model_class(
        CustomCnn, "IMAGE_CLASSIFICATION",
        "synthetic://images?classes=10&n=1024&w=16&h=16&c=3&seed=0",
        "synthetic://images?classes=10&n=256&w=16&h=16&c=3&seed=1",
        queries=rng.uniform(0, 1, size=(4, 16, 16, 3)).tolist(),
    )
    assert len(preds) == 4 and len(preds[0]) == 10
