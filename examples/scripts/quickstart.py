"""Quickstart: the full rafiki-tpu user journey, end to end.

Reference parity: examples/scripts/ (unverified — SURVEY.md §4
"quickstart scripts as integration tests"): create users → upload a
model → train job → inspect trials → inference job → predict.

Run against a live admin (scripts/start.sh):
    python examples/scripts/quickstart.py --host 127.0.0.1 --port 3000
Or fully self-contained (boots an admin in-process):
    python examples/scripts/quickstart.py --standalone
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent.parent
sys.path.insert(0, str(REPO))  # runnable straight from a checkout

TRAIN = "synthetic://images?classes=10&n=2048&seed=0"
VAL = "synthetic://images?classes=10&n=512&seed=1"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=3000)
    ap.add_argument("--standalone", action="store_true",
                    help="boot an in-process admin on an ephemeral port")
    ap.add_argument("--trials", type=int, default=4)
    args = ap.parse_args()

    server = None
    if args.standalone:
        import tempfile
        import threading

        from werkzeug.serving import make_server

        from rafiki_tpu.admin import Admin
        from rafiki_tpu.admin.app import AdminApp
        from rafiki_tpu.config import Config, set_config
        from rafiki_tpu.utils.backend import honor_env_platform

        # JAX_PLATFORMS=cpu must actually stick (the image's
        # sitecustomize would otherwise hijack onto the TPU plugin and
        # hang the scheduler thread when the TPU is unreachable).
        honor_env_platform()

        cfg = Config(data_dir=Path(tempfile.mkdtemp(prefix="rafiki_quickstart_")))
        cfg.ensure_dirs()
        set_config(cfg)
        admin = Admin(config=cfg)
        server = make_server("127.0.0.1", 0, AdminApp(admin), threaded=True)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        args.port = server.server_port
        print(f"standalone admin on port {args.port}")

    from rafiki_tpu.client import Client

    # 1. superadmin logs in and creates the two developer accounts
    sa = Client(args.host, args.port)
    sa.login("superadmin@rafiki", "rafiki")
    for email, role in [("modeldev@example.com", "MODEL_DEVELOPER"),
                        ("appdev@example.com", "APP_DEVELOPER")]:
        try:
            sa.create_user(email, "password", role)
        except Exception:
            pass  # already exists from a previous run

    # 2. the model developer uploads a template
    dev = Client(args.host, args.port)
    dev.login("modeldev@example.com", "password")
    template = REPO / "examples/models/image_classification/custom_cnn.py"
    try:
        dev.create_model("custom_cnn", "IMAGE_CLASSIFICATION", template,
                         "CustomCnn")
        print("uploaded model template custom_cnn")
    except Exception as e:
        print(f"model upload skipped: {e}")

    # 3. the app developer starts a train job
    app_name = f"quickstart_{int(time.time())}"
    appdev = Client(args.host, args.port)
    appdev.login("appdev@example.com", "password")
    appdev.create_train_job(app_name, "IMAGE_CLASSIFICATION", TRAIN, VAL,
                            {"MODEL_TRIAL_COUNT": args.trials},
                            model_names=["custom_cnn"], advisor_kind="gp")
    print(f"train job {app_name} started ({args.trials} trials)...")
    job = appdev.wait_until_train_job_has_stopped(app_name, timeout=3600,
                                                  poll_s=2.0)
    print(f"train job finished: {job['status']}")

    # 4. inspect trials
    for t in appdev.get_trials_of_train_job(app_name):
        score = "—" if t["score"] is None else f"{t['score']:.4f}"
        print(f"  trial {t['no']}: {t['status']:9s} score={score} "
              f"knobs={t['knobs']}")
    best = appdev.get_best_trials_of_train_job(app_name, max_count=2)
    print(f"best score: {best[0]['score']:.4f}")

    # 5. deploy + predict
    inf = appdev.create_inference_job(app_name)
    print(f"inference job RUNNING, predictor at {inf['predictor_host']}")
    from rafiki_tpu.model.dataset import dataset_utils

    ds = dataset_utils.load("synthetic://images?classes=10&n=16&seed=7")
    preds = appdev.predict(app_name, ds.x.tolist())
    import numpy as np

    acc = float(np.mean(np.argmax(np.asarray(preds), -1) == ds.y))
    print(f"ensemble accuracy on 16 fresh queries: {acc:.2f}")
    appdev.stop_inference_job(app_name)
    print("quickstart complete")
    if server is not None:
        server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
